"""File-journal event broker: the hermetic Kafka stand-in.

The reference fork already abandoned live Kafka for its controlled
experiments and read events from a file (``FileBasedDataSource``,
``AdvertisingTopologyNative.java:144-165``, fed by ``events_path``); the
pristine generator likewise journals every event it sends to Kafka into
``kafka-json.txt`` (``core.clj:75,96-97``) so the oracle can replay it.  This
module makes that pattern first-class: a *topic* is an append-only
newline-delimited file in a broker directory, writers append, readers tail
from a byte offset.  Offsets are byte positions, so checkpoint/resume
semantics match Kafka's ``(topic, offset)`` pairs
(``setStartFromEarliest``, ``AdvertisingTopologyNative.java:92``).

The real-Kafka adapter implementing this same contract against
confluent-kafka lives in ``streambench_tpu.io.kafka`` (import-guarded;
the library is absent in this image).  The shared contract both brokers
honor is pinned by ``tests/test_kafka_contract.py``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Iterator


class JournalWriter:
    """Append-only writer for one topic file.  Thread-safe."""

    def __init__(self, path: str, sync_every: int = 0, append: bool = True):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "ab" if append else "wb", buffering=1024 * 1024)
        self._lock = threading.Lock()
        self._sync_every = sync_every
        self._since_sync = 0

    def append(self, line: "str | bytes | memoryview") -> None:
        data = line.encode("utf-8") if isinstance(line, str) else line
        with self._lock:
            self._f.write(data)
            if bytes(data[-1:]) != b"\n":
                self._f.write(b"\n")
            self._since_sync += 1
            if self._sync_every and self._since_sync >= self._sync_every:
                self._f.flush()
                self._since_sync = 0

    def append_many(self, lines: list[str] | list[bytes]) -> None:
        if not lines:
            return
        chunks = []
        for line in lines:
            data = line.encode("utf-8") if isinstance(line, str) else line
            chunks.append(data if data.endswith(b"\n") else data + b"\n")
        with self._lock:
            self._f.write(b"".join(chunks))
            self._since_sync += len(chunks)
            if self._sync_every and self._since_sync >= self._sync_every:
                self._f.flush()
                self._since_sync = 0

    def append_bytes(self, data: "bytes | memoryview") -> None:
        """Append a pre-rendered block of newline-terminated records in one
        write — the zero-copy sink for the native event formatter (the
        producer-side peer of the engine's block-mode ingest; memoryviews
        are written without materializing bytes).  A distinct method so
        sinks without block support fail the caller's ``hasattr``
        capability probe."""
        if data:
            self.append(data)

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.flush()
            self._f.close()

    def __enter__(self) -> "JournalWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class JournalReader:
    """Tailing reader over a topic file, starting at a byte ``offset``.

    ``poll`` returns up to ``max_records`` complete lines (partial trailing
    lines are left in the file until the writer finishes them) together with
    the next offset — the unit a checkpoint persists.
    """

    def __init__(self, path: str, offset: int = 0,
                 byte_budget: int = 4 * 1024 * 1024,
                 skip_corrupt: bool = False):
        self.path = path
        self.offset = offset          # consumed offset (the checkpoint unit)
        self._byte_budget = byte_budget
        self._fh = None
        self._readahead: deque[bytes] = deque()  # parsed but not delivered
        # Torn-tail recovery: a writer that crashed mid-append can leave a
        # NUL-padded partial page in the file (filesystems zero-fill the
        # torn region); once a restarted writer appends past it, the NULs
        # sit inside a "record" no parser can use.  ``skip_corrupt``
        # consumes such records (offset still advances — checkpoints stay
        # byte-exact) without delivering them, counting each in
        # ``corrupt_records``.  Off by default: silently eating records
        # is a policy the operator must opt into.
        self.skip_corrupt = skip_corrupt
        self.corrupt_records = 0

    def backlog_bytes(self) -> int:
        """Bytes appended to the topic but not yet delivered (telemetry:
        the consumer-lag gauge).  A stat + subtraction — safe to call
        from the sampler thread at any cadence."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0
        return max(size - self.offset, 0)

    def seek(self, offset: int) -> None:
        """Reposition to an absolute byte offset (checkpoint restore).

        Assigning ``offset`` directly is not enough once the reader has
        polled: the open file handle and the read-ahead buffer both hold
        the old position and would silently keep delivering from it.
        """
        self.offset = offset
        self._readahead.clear()
        if self._fh is not None:
            self._fh.seek(offset)

    def _ensure_open(self) -> bool:
        if self._fh is None:
            if not os.path.exists(self.path):
                return False
            self._fh = open(self.path, "rb")
            self._fh.seek(self.offset)
        return True

    def poll(self, max_records: int = 65536) -> list[bytes]:
        """Read up to ``max_records`` complete lines from the journal.

        Reads bounded chunks and keeps surplus parsed lines in a read-ahead
        buffer, so each journal byte is read and split exactly once no
        matter the poll granularity; ``offset`` only advances over
        *delivered* lines, preserving checkpoint/resume exactness.

        In ``skip_corrupt`` mode, records with embedded NUL bytes (a
        crashed writer's torn page) are consumed-but-not-delivered and
        counted; the poll may then return fewer lines than available,
        which every caller already tolerates.
        """
        out = self._poll_lines(max_records)
        if self.skip_corrupt and out:
            kept = [l for l in out if b"\x00" not in l]
            if len(kept) != len(out):
                self.corrupt_records += len(out) - len(kept)
                return kept
        return out

    def _poll_lines(self, max_records: int) -> list[bytes]:
        out: list[bytes] = []
        ra = self._readahead
        while ra and len(out) < max_records:
            line = ra.popleft()
            self.offset += len(line) + 1
            out.append(line)
        if len(out) >= max_records or not self._ensure_open():
            return out

        # Loop budget-sized reads until the request is satisfied or the
        # journal runs dry — a single bounded read would silently cap
        # every poll at ~budget/linesize records and leave scan chunks
        # (max_records = K*B) chronically underfilled.
        while len(out) < max_records:
            budget = self._byte_budget
            while True:
                data = self._fh.read(budget)
                if not data:
                    return out
                end = data.rfind(b"\n")
                if end >= 0:
                    break
                if len(data) < budget:
                    # partial trailing line, writer not done yet; rewind
                    self._fh.seek(self._fh.tell() - len(data))
                    return out
                budget *= 2  # one line longer than the budget: retry bigger
                self._fh.seek(self._fh.tell() - len(data))
            # return unread tail (an incomplete line) to the file position
            tail = len(data) - (end + 1)
            if tail:
                self._fh.seek(self._fh.tell() - tail)
            # split on \n only: splitlines() would also split on \r/\v/\f
            # etc. inside a record and corrupt the byte-offset accounting.
            lines = data[:end].split(b"\n")
            take = max_records - len(out)
            for line in lines[:take]:
                self.offset += len(line) + 1
            out.extend(lines[:take])
            ra.extend(lines[take:])
        return out

    def poll_blocking(self, max_records: int = 65536,
                      timeout_s: float = 1.0,
                      poll_interval_s: float = 0.001) -> list[bytes]:
        deadline = time.monotonic() + timeout_s
        while True:
            lines = self.poll(max_records)
            if lines or time.monotonic() >= deadline:
                return lines
            time.sleep(poll_interval_s)

    def poll_block(self, max_bytes: int | None = None) -> bytes:
        """Raw complete-line bytes for block-mode ingest (the native
        encoder scans record boundaries itself; no per-line objects).

        Returns up to ``max_bytes`` ending on a line boundary; ``offset``
        advances over exactly the returned bytes, so checkpoints stay
        record-exact.  Cannot be mixed with line-mode ``poll`` while its
        read-ahead holds parsed-but-undelivered lines.
        """
        if self._readahead:
            raise RuntimeError(
                "poll_block after line-mode poll left read-ahead lines; "
                "one reader must stick to one ingest mode")
        if not self._ensure_open():
            return b""
        budget = max_bytes or self._byte_budget
        while True:
            data = self._fh.read(budget)
            if not data:
                return b""
            end = data.rfind(b"\n")
            if end >= 0:
                break
            if len(data) < budget:
                # partial trailing line, writer not done yet; rewind
                self._fh.seek(self._fh.tell() - len(data))
                return b""
            budget *= 2  # one line longer than the budget: retry bigger
            self._fh.seek(self._fh.tell() - len(data))
        tail = len(data) - (end + 1)
        if tail:
            self._fh.seek(self._fh.tell() - tail)
            data = data[:end + 1]
        self.offset += len(data)
        if self.skip_corrupt and b"\x00" in data:
            # NUL records never reach the block parser: drop the torn
            # lines from the returned block (offset already covers the
            # full read, so checkpoints stay byte-exact).
            lines = data.split(b"\n")
            if lines and not lines[-1]:
                lines.pop()
            kept = [l for l in lines if b"\x00" not in l]
            self.corrupt_records += len(lines) - len(kept)
            data = b"".join(l + b"\n" for l in kept)
        return data

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "JournalReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class MultiReader:
    """Round-robin reader over all partitions of a topic.

    The streaming engine is one consumer of the WHOLE topic (the
    reference's engines likewise subscribe to every partition of
    ``ad-events``); partitioned topics exist so count-windowed map
    partitions can each own one (``map.partitions``).  ``poll`` drains
    partitions round-robin for rough arrival-order fairness.

    Checkpointing: a multi-partition position is a vector, not a byte
    offset — ``offsets``/``seek_offsets`` expose it; the scalar
    ``offset`` property exists only to fail loudly if something treats
    this reader as single-partition.
    """

    def __init__(self, readers: list[JournalReader]):
        if not readers:
            raise ValueError("MultiReader needs at least one reader")
        self._readers = readers
        self._next = 0

    @property
    def offsets(self) -> list[int]:
        return [r.offset for r in self._readers]

    def backlog_bytes(self) -> int:
        """Total undelivered bytes across all partitions (telemetry)."""
        return sum(r.backlog_bytes() for r in self._readers)

    def seek_offsets(self, offsets: list[int]) -> None:
        if len(offsets) != len(self._readers):
            raise ValueError(
                f"{len(offsets)} offsets for {len(self._readers)} partitions")
        for r, off in zip(self._readers, offsets):
            r.seek(off)

    @property
    def offset(self):
        raise AttributeError(
            "MultiReader spans partitions; use .offsets (checkpointing a "
            "multi-partition run needs the per-partition vector)")

    def poll(self, max_records: int = 65536) -> list[bytes]:
        """Drain partitions in bounded round-robin slices.

        Each partition contributes at most ``max_records // n`` per
        sweep, so consumption stays time-balanced across partitions.
        Letting one partition satisfy a whole request (the old behavior)
        skews inter-partition progress by the full request's event-time
        span — enough to push the lagging partitions past allowed
        lateness and silently drop their events once the watermark has
        advanced (Kafka consumers likewise interleave partition fetches).
        """
        out: list[bytes] = []
        n = len(self._readers)
        slice_cap = max(max_records // n, 1)
        empty_streak = 0
        while len(out) < max_records and empty_streak < n:
            r = self._readers[self._next]
            self._next = (self._next + 1) % n
            got = r.poll(max_records=min(slice_cap, max_records - len(out)))
            if got:
                out.extend(got)
                empty_streak = 0
            else:
                empty_streak += 1
        return out

    def close(self) -> None:
        for r in self._readers:
            r.close()

    def __enter__(self) -> "MultiReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FileBroker:
    """Directory of topic files; the process-local 'Kafka cluster'.

    ``create_topic``/``writer``/``reader`` mirror the harness's topic
    lifecycle (``create_kafka_topic``, ``stream-bench.sh:107-115``).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def topic_path(self, topic: str, partition: int = 0) -> str:
        return os.path.join(self.root, f"{topic}-{partition}.jsonl")

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        for p in range(partitions):
            path = self.topic_path(topic, p)
            if not os.path.exists(path):
                open(path, "ab").close()

    def partitions(self, topic: str) -> list[int]:
        pre = f"{topic}-"
        out = []
        for name in os.listdir(self.root):
            if name.startswith(pre) and name.endswith(".jsonl"):
                try:
                    out.append(int(name[len(pre):-6]))
                except ValueError:
                    continue
        return sorted(out)

    def writer(self, topic: str, partition: int = 0,
               append: bool = True) -> JournalWriter:
        return JournalWriter(self.topic_path(topic, partition), append=append)

    def reader(self, topic: str, partition: int = 0,
               offset: int = 0, skip_corrupt: bool = False) -> JournalReader:
        return JournalReader(self.topic_path(topic, partition), offset,
                             skip_corrupt=skip_corrupt)

    def multi_reader(self, topic: str) -> MultiReader:
        """One consumer over every existing partition of ``topic``."""
        parts = self.partitions(topic) or [0]
        return MultiReader([self.reader(topic, p) for p in parts])

    def read_all(self, topic: str) -> Iterator[bytes]:
        """Replay a whole topic (all partitions, offset 0) — oracle use."""
        for p in self.partitions(topic):
            with self.reader(topic, p) as r:
                while True:
                    lines = r.poll()
                    if not lines:
                        break
                    yield from lines
