"""Checkpoint/resume: (journal offset, window-state carry) snapshots.

The reference has NO working checkpointing — Flink's ``enableCheckpointing``
is commented out (``AdvertisingTopologyNative.java:81-84``) and the only
resume semantics are Kafka consumer offsets (``setStartFromEarliest``,
``AdvertisingTopologyNative.java:92``; ``auto.offset.reset=smallest``,
``AdvertisingSpark.scala:64``): crash = recount everything from the earliest
retained offset.  Here checkpointing is cheap and exact, because the whole
engine state is a handful of fixed-shape int32 arrays plus two small host
dicts (SURVEY.md §5.4): one ``np.savez`` per snapshot, written atomically
(tmp file + ``os.replace``) so a crash mid-save can never corrupt the
latest good checkpoint.

Semantics: a snapshot captures the engine *exactly* as of a journal byte
``offset`` — device arrays (count deltas, ring slots, watermark, dropped),
the host pending-delta buffer, the per-window latency ledger, and the
encoder's time base.  Restoring and re-tailing the journal at ``offset``
replays the stream with no loss and no recount **relative to the
snapshot**.  End-to-end the guarantee is at-least-once: Redis window
writes are HINCRBY deltas, so any flush performed after the snapshot a
crash rewinds to is applied again on replay.  The replay window is
bounded by the snapshot cadence — the runner snapshots right after each
flush by default (``jax.checkpoint.interval.ms = 0``), shrinking the
double-count exposure to a crash landing inside one flush→save gap; a
larger interval widens it to every flush since the last snapshot.  This
is the same guarantee class as the reference engines' offset commits
(at-least-once on restart from the last committed Kafka offset).

With ``jax.sink.exactly_once`` on the guarantee tightens to equality
(ROBUSTNESS.md "Exactly-once"): the snapshot additionally carries the
last sink fence it covers (``meta["sink_epoch"]``/``meta["sink_seq"]``),
the cumulative per-window writeback ledger (``extra["xo_totals"]``) and
the tainted-window set (``extra["xo_taint"]``).  On resume the engine
compares the sink's fence against the snapshot's: any flush the crashed
attempt landed — fully or partially — after this snapshot is detected
and the attempt reconciles with absolute ledger writes instead of
replayed increments.  All three fields ride the existing meta/extra
channels, so the format version is unchanged and flag-off snapshots are
byte-identical.
"""

from __future__ import annotations

import json
import os
import tempfile
import zipfile
from dataclasses import dataclass, field

import numpy as np

# v2: offset may be a per-partition vector (multi-partition topics).
# v1 snapshots (scalar offset) remain readable.
FORMAT_VERSION = 2
READABLE_VERSIONS = (1, 2)


class CheckpointVersionError(RuntimeError):
    """Checkpoint written by an incompatible format version.

    Deliberately NOT treated as a torn file by ``Checkpointer.load``:
    silently skipping a version-mismatched snapshot would restart the
    engine from offset 0 and replay the whole journal into persistent
    Redis counts.  The operator must migrate or discard explicitly.
    """


@dataclass
class Snapshot:
    """One engine checkpoint, decoded (see ``AdAnalyticsEngine.restore``).

    ``offset`` is the journal position to re-tail from: a single int for
    one partition, or a per-partition vector (``MultiReader.offsets``)
    for a multi-partition topic — the Kafka committed-offset-vector
    analog (``AdvertisingTopologyNative.java:92``).
    """

    offset: int | list[int]
    meta: dict
    counts: np.ndarray        # [C, W] int32 undrained device deltas
    window_ids: np.ndarray    # [W] int32
    watermark: int
    dropped: int
    pending: list[tuple[int, int, int]] = field(default_factory=list)
    latency: list[tuple[int, int]] = field(default_factory=list)
    # Engine-specific payload (sketch engines: HLL registers, t-digest
    # centroids, CMS table, session carries, intern tables).  Arrays of
    # any dtype incl. bytes ("S*"); round-trips through the npz untouched.
    extra: dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def seq(self) -> int:
        return int(self.meta.get("seq", 0))


def _encode(snapshot: Snapshot) -> dict:
    pending = np.asarray(snapshot.pending, np.int64).reshape(-1, 3)
    latency = np.asarray(snapshot.latency, np.int64).reshape(-1, 2)
    offset = (list(map(int, snapshot.offset))
              if isinstance(snapshot.offset, (list, tuple))
              else int(snapshot.offset))
    meta = dict(snapshot.meta)
    meta.update(version=FORMAT_VERSION, offset=offset,
                watermark=int(snapshot.watermark),
                dropped=int(snapshot.dropped))
    out = dict(
        counts=np.asarray(snapshot.counts, np.int32),
        window_ids=np.asarray(snapshot.window_ids, np.int32),
        pending=pending,
        latency=latency,
        meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
    )
    for name, arr in snapshot.extra.items():
        out[f"x_{name}"] = np.asarray(arr)
    return out


def _decode(z) -> Snapshot:
    meta = json.loads(bytes(z["meta"].tobytes()).decode())
    if meta.get("version") not in READABLE_VERSIONS:
        raise CheckpointVersionError(
            f"unsupported checkpoint version {meta.get('version')} "
            f"(this build reads {READABLE_VERSIONS})")
    off = meta["offset"]
    return Snapshot(
        offset=[int(o) for o in off] if isinstance(off, list) else int(off),
        meta=meta,
        counts=z["counts"],
        window_ids=z["window_ids"],
        watermark=int(meta["watermark"]),
        dropped=int(meta["dropped"]),
        pending=[tuple(r) for r in z["pending"].tolist()],
        latency=[tuple(r) for r in z["latency"].tolist()],
        extra={name[2:]: z[name] for name in z.files
               if name.startswith("x_")},
    )


class Checkpointer:
    """Rotating atomic snapshots in a directory.

    ``save`` writes ``ckpt-<seq>.npz`` via tmp-file + ``os.replace`` and
    prunes all but the newest ``keep``; ``load`` returns the newest
    readable snapshot (a torn file from a crash mid-save is skipped, not
    fatal).
    """

    def __init__(self, directory: str, keep: int = 2):
        self.directory = directory
        self.keep = max(keep, 1)
        os.makedirs(directory, exist_ok=True)
        self._seq = max((s for s, _ in self._existing()), default=-1) + 1

    def _existing(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt-") and name.endswith(".npz"):
                try:
                    out.append((int(name[5:-4]),
                                os.path.join(self.directory, name)))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, snapshot: Snapshot) -> str:
        snapshot.meta["seq"] = self._seq
        path = os.path.join(self.directory, f"ckpt-{self._seq:08d}.npz")
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **_encode(snapshot))
                f.flush()
                os.fsync(f.fileno())  # rename-before-data = torn npz
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        self._seq += 1
        for _, old in self._existing()[:-self.keep]:
            os.unlink(old)
        return path

    def load(self) -> Snapshot | None:
        for _, path in reversed(self._existing()):
            try:
                with np.load(path) as z:
                    return _decode(z)
            except (OSError, ValueError, KeyError, zipfile.BadZipFile):
                continue  # torn/corrupt file: fall back to an older one
        return None
