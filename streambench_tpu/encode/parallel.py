"""Parallel micro-batch encoding: per-thread encoders behind one pool.

The encode stage is the TPU engine's deserialize bottleneck (SURVEY.md §7
"hard parts": string->index encoding at line rate).  The native scanner
is one ctypes call per batch — ctypes releases the GIL for the call's
duration — so N worker threads with N independent encoder instances
parallelize it near-linearly.

Soundness: worker encoders intern user/page ids INDEPENDENTLY, so their
``user_idx``/``page_idx`` columns are not comparable across batches.
That is fine for the exact-count engine family, whose kernel reads only
``ad_idx``/``event_type``/``event_time``/``valid`` (the ad table is
fixed up front and shared read-only).  Sketch engines key device state
by interned indices and MUST NOT use this pool
(``_SketchEngineBase.PARALLEL_ENCODE_OK = False``).

Time rebasing: all encoders must share one ``base_time_ms`` or window
ids would shift between batches.  The pool pins the primary encoder's
base (encoding the first-ever batch sequentially to establish it) and
syncs every worker before its job runs.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

from streambench_tpu.encode.encoder import repack_batches


class ParallelEncodePool:
    def __init__(self, primary, factory: Callable[[], object],
                 workers: int = 4):
        self.primary = primary
        self._factory = factory
        self._workers = max(workers, 1)
        self._tls = threading.local()
        self._pool = ThreadPoolExecutor(max_workers=self._workers,
                                        thread_name_prefix="encode")

    def _worker_enc(self, base: int):
        """Thread-local worker encoder, base-synced to the primary's
        rebase origin (shared by the line and block jobs — any new
        worker-setup step belongs HERE so the two paths cannot drift)."""
        enc = getattr(self._tls, "enc", None)
        if enc is None:
            enc = self._tls.enc = self._factory()
        if enc.base_time_ms != base:
            enc.set_base_time(base)
        return enc

    def _job(self, lines: list[bytes], batch_size: int, base: int):
        return self._worker_enc(base).encode(lines, batch_size)

    def encode_chunks(self, chunks: list[list[bytes]], batch_size: int):
        """Encode each chunk into an ``EncodedBatch``, order-preserving."""
        out = [None] * len(chunks)
        start = 0
        if self.primary.base_time_ms is None and chunks:
            # First data ever: establish the shared rebase origin on the
            # primary before any worker encodes against it.
            out[0] = self.primary.encode(chunks[0], batch_size)
            start = 1
            if self.primary.base_time_ms is None:
                # all-bad first chunk: no base yet; stay sequential
                for i in range(start, len(chunks)):
                    out[i] = self.primary.encode(chunks[i], batch_size)
                return out
        base = self.primary.base_time_ms
        futures = [(i, self._pool.submit(self._job, chunks[i],
                                         batch_size, base))
                   for i in range(start, len(chunks))]
        for i, fut in futures:
            out[i] = fut.result()
        return out

    def _job_block(self, data: bytes, batch_size: int, base: int,
                   start: int, end: int):
        return self._worker_enc(base).carve_block(
            data, batch_size, start=start, end=end)

    def carve_block_parallel(self, data: bytes, batch_size: int
                             ) -> tuple[list, int]:
        """Carve + parse one raw journal block on all workers.

        Record boundaries are found first (a memchr per cut — ~free),
        then each worker scans its region of the SHARED block via the
        start/end bounds (no sub-block copies).  Worker tails are
        partial batches, so the results are repacked into full batches
        before the device sees them.  Same (batches, consumed) contract
        as ``carve_block``; an unterminated trailing record is left
        unconsumed.
        """
        n = len(data)
        start = 0
        head: list = []
        if self.primary.base_time_ms is None and n:
            # First data ever: establish the shared rebase origin by
            # encoding one batch on the primary before workers spread out.
            head, start = self.primary.carve_block(data, batch_size,
                                                   max_batches=1)
            if self.primary.base_time_ms is None:
                return head, start  # all-bad head: no base to share yet
        base = self.primary.base_time_ms
        # record-aligned cut points over [start, n)
        cuts = [start]
        for i in range(1, self._workers):
            want = start + (n - start) * i // self._workers
            pos = data.find(b"\n", max(want, cuts[-1]))
            cuts.append(pos + 1 if pos >= 0 else n)
        cuts.append(n)
        jobs = [(a, b, self._pool.submit(self._job_block, data,
                                         batch_size, base, a, b))
                for a, b in zip(cuts, cuts[1:]) if a < b]
        batches = head
        for a, b, fut in jobs:
            got, stop = fut.result()
            batches += got
            # The reported consumption below assumes every worker parsed
            # its whole region (interior cuts are newline-aligned; the
            # last region may hold an unterminated tail).  Verify with
            # the stop offset the worker actually reached — an early
            # stop would silently drop records while still reporting
            # them consumed.
            expect = (b if data[b - 1] == 0x0A
                      else max(data.rfind(b"\n", a, b) + 1, a))
            if stop != expect:
                raise RuntimeError(
                    f"parallel carve worker stopped at {stop}, expected "
                    f"{expect} for region [{a}, {b})")
        # consumption: everything but an unterminated trailing record
        nl_end = data.rfind(b"\n") + 1
        return repack_batches(batches, batch_size), max(start, nl_end)

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
