"""Host-side event encoding: JSON lines -> fixed-shape int32 columnar batches.

This is the TPU analog of the JVM engines' deserialize stage
(``DeserializeBolt``, ``storm-benchmarks/.../AdvertisingTopology.java:44-70``)
— but instead of producing per-tuple objects, it produces *columns*: dense
int32 index arrays that a jitted aggregation step can gather/scatter on.
Everything dynamic (UUIDs, strings, JSON) dies here, at the host boundary;
nothing string-shaped ever reaches the device.  This mirrors the design of
the fork's mmap'd columnar handoff experiment (``WindowedArrowFormatBolter``,
``AdvertisingTopologyNative.java:278-356``): row->column transposition on the
host, fixed-layout buffers to the compute engine.

Two parser paths share one contract:

- a *fast path* that exploits the generator's fixed JSON field order
  (``make-kafka-event-at``, ``core.clj:175-181``): split on ``"`` and read
  values at fixed token positions, with a cheap layout check per line;
- a *fallback* (``json.loads``) for any line the fast path rejects, so
  hand-crafted or re-ordered JSON still parses.

A native C++ path (``streambench_tpu.native``) can replace both when built;
the contract (EncodedBatch columns) is identical.

Timestamps are rebased to ``base_time_ms`` so all device arithmetic stays in
int32 (TPU-friendly; JAX x64 stays off): 2^31 ms of relative room ~= 24 days.
"""

from __future__ import annotations

import json
import zlib
from dataclasses import dataclass

import numpy as np


def _id_hash32(b: bytes) -> int:
    """Stateless 32-bit id hash (crc32, as a signed int32 bit pattern).
    Must stay bit-identical to ``crc32b`` in native/encoder.cpp — the
    differential tests pin this."""
    c = zlib.crc32(b)
    return c - (1 << 32) if c & 0x80000000 else c

AD_TYPES = ("banner", "modal", "sponsored-search", "mail", "mobile")
EVENT_TYPES = ("view", "click", "purchase")
AD_TYPE_INDEX = {t: i for i, t in enumerate(AD_TYPES)}
EVENT_TYPE_INDEX = {t: i for i, t in enumerate(EVENT_TYPES)}
VIEW = EVENT_TYPE_INDEX["view"]
# bytes-keyed twins for the hot parse loop (no per-row decode)
AD_TYPE_INDEX_B = {t.encode(): i for i, t in enumerate(AD_TYPES)}
EVENT_TYPE_INDEX_B = {t.encode(): i for i, t in enumerate(EVENT_TYPES)}


@dataclass
class EncodedBatch:
    """One fixed-shape columnar micro-batch.

    ``valid`` marks real rows; the tail of a ragged batch is padding
    (ad_idx 0, times 0) that every kernel masks out.  ``n`` is the count of
    valid rows.
    """

    ad_idx: np.ndarray       # int32 [B] index into the join table; -1 unknown
    event_type: np.ndarray   # int32 [B] index into EVENT_TYPES; -1 unknown
    event_time: np.ndarray   # int32 [B] ms relative to base_time_ms
    user_idx: np.ndarray     # int32 [B] dense user index (interned)
    page_idx: np.ndarray     # int32 [B] dense page index (interned)
    ad_type: np.ndarray      # int32 [B] index into AD_TYPES; -1 unknown
    valid: np.ndarray        # bool  [B]
    n: int = 0
    base_time_ms: int = 0

    @property
    def batch_size(self) -> int:
        return len(self.ad_idx)


_BATCH_COLS = ("ad_idx", "event_type", "event_time", "user_idx",
               "page_idx", "ad_type")
_COL_PAD = {"event_type": -1, "ad_type": -1}


def repack_batches(batches: list[EncodedBatch],
                   batch_size: int) -> list[EncodedBatch]:
    """Merge a run of batches into the minimum number of full batches,
    preserving event order.

    Parallel sub-block carving yields one partial tail batch per worker;
    folding those as-is would cost a full fixed-shape device step each
    (a quarter-filled batch prices like a full one).  The repack is a
    per-column memcpy (~28 bytes/event) — noise next to the ~250
    bytes/event parse it follows.  All inputs must share one
    ``base_time_ms`` (enforced): merging differently-based rows would
    corrupt every merged timestamp.
    """
    if all(b.n == b.batch_size == batch_size for b in batches):
        return batches
    bases = {b.base_time_ms for b in batches}
    if len(bases) > 1:
        raise ValueError(f"cannot repack mixed-base batches: {bases}")
    cols = {name: np.concatenate([getattr(b, name)[:b.n] for b in batches])
            for name in _BATCH_COLS}
    total = int(cols["ad_idx"].shape[0])
    out: list[EncodedBatch] = []
    for off in range(0, total, batch_size):
        n = min(batch_size, total - off)
        kw = {}
        for name in _BATCH_COLS:
            col = np.full(batch_size, _COL_PAD.get(name, 0), np.int32)
            col[:n] = cols[name][off:off + n]
            kw[name] = col
        valid = np.zeros(batch_size, bool)
        valid[:n] = True
        out.append(EncodedBatch(valid=valid, n=n,
                                base_time_ms=batches[0].base_time_ms, **kw))
    return out


class EventEncoder:
    """Stateful interning encoder.

    The ad->index map is fixed up front from the join table (1,000 ads,
    ``RedisAdCampaignCache`` semantics: the join side is known data); user
    and page ids are interned on first sight, unbounded, like the reference's
    in-process LRU caches but without eviction (a uuid string + int is ~100
    bytes; 10^6 users ~= 100 MB, acceptable for benchmark runs).

    ``RELEASES_GIL`` marks whether ``encode`` spends its time outside the
    GIL (the native subclass's ctypes call does) — the signal the
    parallel encode pool uses; threading a GIL-bound encoder is pure
    overhead.
    """

    RELEASES_GIL = False

    def set_intern_ids(self, on: bool) -> None:
        """Disable/enable user/page interning.  Engines whose kernels
        never read the interned columns (exact counts, sliding windows)
        turn it off: the per-row hash probes are the biggest per-event
        cost after tokenization, and the columns then carry zeros."""
        self.intern_ids = bool(on)

    def set_hash_ids(self, on: bool) -> None:
        """STATELESS id columns: user/page_idx = crc32 of the id bytes
        instead of intern indices.  For kernels that only need a
        well-mixed identity (HLL cardinality — which splitmix-hashes the
        column anyway, so a 32-bit string hash loses nothing), this makes
        the columns consistent across independent encoders (parallel
        encode pools, micro-batch partitions) and across process
        restarts, with no intern table to snapshot.  Kernels that index
        arrays by the column (session rows) must keep interning."""
        self.hash_ids = bool(on)

    def __init__(self, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 divisor_ms: int = 10_000, lateness_ms: int = 60_000):
        # Window length + allowed lateness drive the base-time rebase; they
        # MUST match what the engine passes to ops.windowcount.step, or
        # windows misalign / legitimately-late events go negative.
        self.divisor_ms = divisor_ms
        self.lateness_ms = lateness_ms
        # Deterministic campaign indexing: sorted unless an order is given.
        if campaigns is None:
            campaigns = sorted(set(ad_to_campaign.values()))
        self.campaigns: list[str] = list(campaigns)
        self.campaign_index = {c: i for i, c in enumerate(self.campaigns)}
        self.ads: list[str] = list(ad_to_campaign.keys())
        # bytes-keyed: the hot loop parses bytes and must not decode per row
        self.ad_index = {a.encode(): i for i, a in enumerate(self.ads)}
        # join_table[ad_idx] -> campaign_idx ; one trailing row for "unknown"
        jt = np.fromiter(
            (self.campaign_index[ad_to_campaign[a]] for a in self.ads),
            dtype=np.int32, count=len(self.ads))
        self.join_table = np.concatenate([jt, np.array([-1], np.int32)])
        self.unknown_ad = len(self.ads)   # maps to campaign -1
        self.user_index: dict[bytes, int] = {}
        self.page_index: dict[bytes, int] = {}
        self.intern_ids = True
        self.hash_ids = False
        self.base_time_ms: int | None = None
        self.fallback_lines = 0
        self.bad_lines = 0
        # Dead-letter sink (optional): malformed lines are appended here
        # raw instead of only being counted — the reference silently drops
        # bad tuples; a DLQ keeps them replayable after a parser fix.
        self._deadletter = None
        self.dlq_lines = 0

    @property
    def num_campaigns(self) -> int:
        return len(self.campaigns)

    def set_deadletter(self, sink) -> None:
        """Attach a dead-letter sink (anything with ``append(bytes)``,
        e.g. a ``JournalWriter`` on a ``<topic>-deadletter`` topic).
        Every line that would only bump ``bad_lines`` is also appended
        raw; both encoder paths (fast/fallback, Python/native) reject
        through the same counting sites, so the DLQ sees every reject."""
        self._deadletter = sink

    def _reject(self, line: bytes) -> None:
        """One malformed line: count it, and dead-letter it if a sink is
        attached (the ONLY place ``bad_lines`` is allowed to grow)."""
        self.bad_lines += 1
        if self._deadletter is not None:
            self._deadletter.append(bytes(line))
            self.dlq_lines += 1

    def set_base_time(self, base_time_ms: int | None) -> None:
        """Pin the rebase origin (checkpoint restore): window ids are
        relative to ``base_time_ms``, so a restored engine must encode new
        events against the *same* base or its ring slots would shift."""
        self.base_time_ms = base_time_ms

    # -- intern-table snapshot (checkpoint/resume for sketch engines) --
    def dump_intern_tables(self) -> tuple[list[bytes], list[bytes]]:
        """User/page id keys in INDEX ORDER.  Sketch state keyed by
        interned indices (HLL register hashes, CMS/session rows) is only
        restorable if a resumed encoder re-assigns identical indices."""
        # _intern only appends (idx == len(table)), so dict insertion
        # order IS index order — no sort needed on the checkpoint path.
        return list(self.user_index), list(self.page_index)

    def restore_intern_tables(self, users: list[bytes],
                              pages: list[bytes]) -> None:
        """Re-intern dumped keys; indices land exactly as dumped."""
        if self.user_index or self.page_index:
            raise ValueError(
                "restore_intern_tables on a used encoder: intern indices "
                "would diverge from the snapshot; restore into a fresh "
                "engine instead")
        self.user_index = {bytes(u): i for i, u in enumerate(users)}
        self.page_index = {bytes(p): i for i, p in enumerate(pages)}

    # -- interning helpers --------------------------------------------
    def user_key(self, idx: int) -> bytes:
        """Reverse lookup: interned index -> user id.  Amortized O(1):
        the index-order list is rebuilt only when the table grew since
        the last call (insertion order IS index order; _intern only
        appends), so k lookups at report time don't each pay an O(users)
        scan."""
        cache = getattr(self, "_user_key_cache", None)
        if cache is None or len(cache) != len(self.user_index):
            cache = self._user_key_cache = list(self.user_index)
        return cache[idx]

    def num_interned_users(self) -> int:
        """Interned-user count (session engines size legacy-snapshot
        reseeding by it; the native encoder reads its C-side table)."""
        return len(self.user_index)

    def _intern(self, table: dict[bytes, int], key: bytes) -> int:
        idx = table.get(key)
        if idx is None:
            idx = len(table)
            table[key] = idx
        return idx

    def _ad_lookup(self, ad: bytes) -> int:
        idx = self.ad_index.get(ad)
        return self.unknown_ad if idx is None else idx

    def _rebase(self, t: int) -> None:
        # Rebase a full lateness span below the first event's window start
        # so even maximally-late events (core.clj:170-173) keep
        # non-negative relative times.
        self.base_time_ms = t - (t % self.divisor_ms) - self.lateness_ms

    # -- parsing ------------------------------------------------------
    # Fast-path layout: the generator's field order, split on '"' gives
    # values at fixed positions (keys at even check positions).
    _FAST_KEYS = (b"user_id", b"page_id", b"ad_id", b"ad_type",
                  b"event_type", b"event_time")

    def _parse_fast(self, line: bytes):
        parts = line.split(b'"')
        # layout: {, user_id, :, <u>, , page_id, :, <p>, ... 27+ tokens
        if len(parts) < 26:
            return None
        if (parts[1] != b"user_id" or parts[5] != b"page_id"
                or parts[9] != b"ad_id" or parts[13] != b"ad_type"
                or parts[17] != b"event_type" or parts[21] != b"event_time"):
            return None
        try:
            t = int(parts[23])
        except ValueError:
            return None
        return parts[3], parts[7], parts[11], parts[15], parts[19], t

    def _parse_slow(self, line: bytes):
        try:
            ev = json.loads(line)
            return (
                str(ev["user_id"]).encode(),
                str(ev["page_id"]).encode(),
                str(ev["ad_id"]).encode(),
                str(ev.get("ad_type", "")).encode(),
                str(ev["event_type"]).encode(),
                int(ev["event_time"]),
            )
        except (KeyError, ValueError, TypeError):
            return None

    def encode(self, lines: list[bytes], batch_size: int | None = None
               ) -> EncodedBatch:
        """Encode ``lines`` into one EncodedBatch padded to ``batch_size``.

        ``len(lines)`` must be <= batch_size; unparseable lines are counted
        in ``bad_lines`` and become invalid (masked) rows.
        """
        B = batch_size if batch_size is not None else len(lines)
        if len(lines) > B:
            raise ValueError(f"{len(lines)} lines exceed batch size {B}")
        ad_idx = np.zeros(B, np.int32)
        etype = np.full(B, -1, np.int32)
        etime = np.zeros(B, np.int32)
        user_idx = np.zeros(B, np.int32)
        page_idx = np.zeros(B, np.int32)
        ad_type = np.full(B, -1, np.int32)
        valid = np.zeros(B, bool)

        n = 0
        for line in lines:
            rec = self._parse_fast(line)
            if rec is None:
                self.fallback_lines += 1
                rec = self._parse_slow(line)
                if rec is None:
                    self._reject(line)
                    continue
            u, p, ad, at, et, t = rec
            if self.base_time_ms is None:
                self._rebase(t)
            if not (-2**31 <= t - self.base_time_ms < 2**31):
                # rebased time must fit the int32 column; an absurd
                # timestamp (clock garbage, fuzzed input) is a bad
                # line, not a crash or a silent int32 wrap (every
                # encoder arm applies this same rule)
                self._reject(line)
                continue
            i = n
            ad_idx[i] = self._ad_lookup(ad)
            etype[i] = EVENT_TYPE_INDEX_B.get(et, -1)
            etime[i] = t - self.base_time_ms
            if self.hash_ids:
                user_idx[i] = _id_hash32(u)
                page_idx[i] = _id_hash32(p)
            elif self.intern_ids:
                user_idx[i] = self._intern(self.user_index, u)
                page_idx[i] = self._intern(self.page_index, p)
            ad_type[i] = AD_TYPE_INDEX_B.get(at, -1)
            valid[i] = True
            n += 1

        return EncodedBatch(ad_idx, etype, etime, user_idx, page_idx,
                            ad_type, valid, n=n,
                            base_time_ms=self.base_time_ms or 0)

    def encode_tbl(self, lines: list[bytes], batch_size: int | None = None
                   ) -> EncodedBatch:
        """Encode the fork's pipe-separated ``events.tbl`` format
        (``u|p|ad|ad_type|event_type|time``; emitted at
        ``AdvertisingTopologyNative.java:210-222``)."""
        B = batch_size if batch_size is not None else len(lines)
        converted = []
        for line in lines:
            f = line.rstrip(b"\n").split(b"|")
            if len(f) < 6:
                self._reject(line)
                continue
            converted.append((line, f))
        if len(converted) > B:
            raise ValueError(f"{len(converted)} lines exceed batch size {B}")
        ad_idx = np.zeros(B, np.int32)
        etype = np.full(B, -1, np.int32)
        etime = np.zeros(B, np.int32)
        user_idx = np.zeros(B, np.int32)
        page_idx = np.zeros(B, np.int32)
        ad_type = np.full(B, -1, np.int32)
        valid = np.zeros(B, bool)
        n = 0
        for line, c in converted:
            u, p, ad, at, et, t = c[:6]
            try:
                ti = int(t)
            except ValueError:
                self._reject(line)
                continue
            if self.base_time_ms is None:
                self._rebase(ti)
            if not (-2**31 <= ti - self.base_time_ms < 2**31):
                self._reject(line)   # same int32-fit rule as encode()
                continue
            ad_idx[n] = self._ad_lookup(ad)
            etype[n] = EVENT_TYPE_INDEX_B.get(et, -1)
            etime[n] = ti - self.base_time_ms
            if self.hash_ids:
                user_idx[n] = _id_hash32(u)
                page_idx[n] = _id_hash32(p)
            elif self.intern_ids:
                user_idx[n] = self._intern(self.user_index, u)
                page_idx[n] = self._intern(self.page_index, p)
            ad_type[n] = AD_TYPE_INDEX_B.get(at, -1)
            valid[n] = True
            n += 1
        return EncodedBatch(ad_idx, etype, etime, user_idx, page_idx,
                            ad_type, valid, n=n,
                            base_time_ms=self.base_time_ms or 0)
