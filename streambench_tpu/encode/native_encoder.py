"""Native-backed EventEncoder: same contract, C++ hot path.

Drop-in subclass of ``EventEncoder``: the fixed-layout JSON scan, string
interning, and column fill run in ``libsbnative.so``; only lines the native
scanner rejects (layout mismatch) take the Python ``json.loads`` fallback,
interned through the same native maps so indices stay consistent.

Use ``make_encoder()`` to get the native version when the library builds
and the pure-Python one otherwise.
"""

from __future__ import annotations

import ctypes
import json

import numpy as np

from streambench_tpu import native
from streambench_tpu.encode.encoder import (
    AD_TYPE_INDEX,
    EVENT_TYPE_INDEX,
    EncodedBatch,
    EventEncoder,
    _id_hash32,
)


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


# Mirrors kBaseUnset in encoder.cpp: INT64_MIN marks "no base yet".  A
# plain "< 0" check would conflate unset with the legitimately negative
# bases produced by small (synthetic/test) event times.
BASE_UNSET = -(1 << 63)


class NativeEventEncoder(EventEncoder):
    RELEASES_GIL = True  # the ctypes scan runs GIL-free (see base class)

    def __init__(self, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 divisor_ms: int = 10_000, lateness_ms: int = 60_000):
        super().__init__(ad_to_campaign, campaigns,
                         divisor_ms=divisor_ms, lateness_ms=lateness_ms)
        lib = native.load()
        if lib is None:
            raise RuntimeError("native encoder library unavailable")
        self._lib = lib
        ads_b = [a.encode() for a in self.ads]
        offsets = np.zeros(len(ads_b) + 1, np.int64)
        np.cumsum([len(a) for a in ads_b], out=offsets[1:])
        self._enc = lib.sb_encoder_new(
            b"".join(ads_b),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(ads_b), divisor_ms, lateness_ms)

    def set_intern_ids(self, on: bool) -> None:
        super().set_intern_ids(on)
        self._lib.sb_encoder_set_intern_ids(self._enc, 1 if on else 0)

    def set_hash_ids(self, on: bool) -> None:
        super().set_hash_ids(on)  # python encode_tbl fallback shares it
        self._lib.sb_encoder_set_hash_ids(self._enc, 1 if on else 0)

    def set_base_time(self, base_time_ms: int | None) -> None:
        super().set_base_time(base_time_ms)
        self._lib.sb_encoder_set_base_time(
            self._enc, BASE_UNSET if base_time_ms is None else base_time_ms)

    def dump_intern_tables(self) -> tuple[list[bytes], list[bytes]]:
        out = []
        for n_fn, bytes_fn, dump_fn in (
                (self._lib.sb_encoder_n_users,
                 self._lib.sb_encoder_users_bytes,
                 self._lib.sb_encoder_dump_users),
                (self._lib.sb_encoder_n_pages,
                 self._lib.sb_encoder_pages_bytes,
                 self._lib.sb_encoder_dump_pages)):
            n = int(n_fn(self._enc))
            buf = ctypes.create_string_buffer(max(int(bytes_fn(self._enc)), 1))
            offsets = np.zeros(n + 1, np.int64)
            dump_fn(self._enc, buf,
                    offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            raw = buf.raw
            out.append([raw[offsets[i]:offsets[i + 1]] for i in range(n)])
        return out[0], out[1]

    def user_key(self, idx: int) -> bytes:
        """Reverse lookup of an interned user index (heavy-hitter
        reports): the C-side table dumps once and re-dumps only when a
        newer index appears."""
        cache = getattr(self, "_user_key_cache", None)
        if cache is None or idx >= len(cache):
            cache, _ = self.dump_intern_tables()
            self._user_key_cache = cache
        return cache[idx]

    def num_interned_users(self) -> int:
        return int(self._lib.sb_encoder_n_users(self._enc))

    def _intern(self, table: dict, key: bytes) -> int:
        """Python-side parse paths (the tbl wire format, encode_tbl)
        must intern through the SAME C-side maps the native scanner
        uses — a Python-dict side table would make reverse lookups and
        intern snapshots see only part of the universe."""
        fn = (self._lib.sb_intern_user if table is self.user_index
              else self._lib.sb_intern_page)
        return int(fn(self._enc, key, len(key)))

    def restore_intern_tables(self, users: list[bytes],
                              pages: list[bytes]) -> None:
        if self._lib.sb_encoder_n_users(self._enc) or \
                self._lib.sb_encoder_n_pages(self._enc):
            raise ValueError(
                "restore_intern_tables on a used encoder: intern indices "
                "would diverge from the snapshot; restore into a fresh "
                "engine instead")
        for table, fn, keys in (("user", self._lib.sb_intern_user, users),
                                ("page", self._lib.sb_intern_page, pages)):
            for i, k in enumerate(keys):
                got = fn(self._enc, bytes(k), len(k))
                if got != i:
                    raise ValueError(
                        f"{table} intern diverged on restore: key {k!r} "
                        f"re-interned to {got}, snapshot says {i} "
                        "(duplicate or corrupted dump?)")

    def __del__(self):  # pragma: no cover - interpreter teardown order
        lib = getattr(self, "_lib", None)
        enc = getattr(self, "_enc", None)
        if lib is not None and enc is not None:
            lib.sb_encoder_free(enc)

    def encode(self, lines: list[bytes], batch_size: int | None = None
               ) -> EncodedBatch:
        B = batch_size if batch_size is not None else len(lines)
        nl = len(lines)
        if nl > B:
            raise ValueError(f"{nl} lines exceed batch size {B}")
        buf = b"".join(lines)
        offsets = np.zeros(nl + 1, np.int64)
        np.cumsum([len(l) for l in lines], out=offsets[1:])

        ad_idx = np.zeros(B, np.int32)
        etype = np.full(B, -1, np.int32)
        etime = np.zeros(B, np.int32)
        user_idx = np.zeros(B, np.int32)
        page_idx = np.zeros(B, np.int32)
        ad_type = np.full(B, -1, np.int32)
        status = np.zeros(B, np.uint8)

        self._lib.sb_encode_json(
            self._enc, buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), nl,
            _i32p(ad_idx), _i32p(etype), _i32p(etime), _i32p(user_idx),
            _i32p(page_idx), _i32p(ad_type),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))

        # Python fallback for layout-mismatch lines (rare: hand-written or
        # re-ordered JSON), through the native intern maps.
        for i in np.flatnonzero(status[:nl] == 2).tolist():
            self.fallback_lines += 1
            rec = self._parse_fallback(lines[i])
            if rec is None:
                self._reject(lines[i])
                status[i] = 0
                continue
            (ad_idx[i], etype[i], etime[i], user_idx[i], page_idx[i],
             ad_type[i]) = rec
            status[i] = 1

        valid = status == 1
        n = int(valid.sum())
        if n != nl:
            # compact valid rows to the front (engine reads [:n]); tail
            # rows revert to the padding defaults (ad 0 / types -1 / t 0)
            keep = np.flatnonzero(valid)
            for col, pad in ((ad_idx, 0), (etype, -1), (etime, 0),
                             (user_idx, 0), (page_idx, 0), (ad_type, -1)):
                col[:n] = col[keep]
                col[n:] = pad
            valid = np.zeros(B, bool)
            valid[:n] = True
        self.base_time_ms = base = self._lib.sb_encoder_base_time(self._enc)
        if base == BASE_UNSET:
            self.base_time_ms = None
        return EncodedBatch(ad_idx, etype, etime, user_idx, page_idx,
                            ad_type, valid, n=n,
                            base_time_ms=self.base_time_ms
                            if self.base_time_ms is not None else 0)

    def encode_block(self, data: bytes, batch_size: int,
                     start: int = 0,
                     end: int | None = None) -> tuple[EncodedBatch, int]:
        """Encode up to ``batch_size`` records straight from a raw
        journal block (complete newline-delimited lines), starting at
        byte ``start`` and never reading past ``end`` (default: the
        whole block).  Returns ``(batch, consumed_bytes)``.

        This is the zero-copy ingest path: no per-line bytes objects,
        no join/offsets round trip — the C scanner finds record
        boundaries (memchr) and parses in the same pass.  An incomplete
        trailing record is not consumed.  The ``end`` bound lets several
        workers scan disjoint regions of ONE shared block without
        slicing (a slice would copy megabytes per sub-block).
        """
        B = batch_size
        bound = len(data) if end is None else min(end, len(data))
        ad_idx = np.zeros(B, np.int32)
        etype = np.full(B, -1, np.int32)
        etime = np.zeros(B, np.int32)
        user_idx = np.zeros(B, np.int32)
        page_idx = np.zeros(B, np.int32)
        ad_type = np.full(B, -1, np.int32)
        status = np.zeros(B, np.uint8)
        rec_off = np.zeros(B + 1, np.int64)

        nl = int(self._lib.sb_encode_block(
            self._enc, data, bound, start, B,
            _i32p(ad_idx), _i32p(etype), _i32p(etime), _i32p(user_idx),
            _i32p(page_idx), _i32p(ad_type),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            rec_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))))
        consumed = int(rec_off[nl]) - start

        # Python fallback for layout-mismatch records (slice them back
        # out of the block via the recorded offsets; newline-terminated)
        for i in np.flatnonzero(status[:nl] == 2).tolist():
            # rec_off[i + 1] always exists (i < nl): the record's end + 1
            line = data[int(rec_off[i]):int(rec_off[i + 1]) - 1]
            self.fallback_lines += 1
            rec = self._parse_fallback(line)
            if rec is None:
                self._reject(line)
                status[i] = 0
                continue
            (ad_idx[i], etype[i], etime[i], user_idx[i], page_idx[i],
             ad_type[i]) = rec
            status[i] = 1

        valid = status == 1
        n = int(valid.sum())
        if n != nl:
            keep = np.flatnonzero(valid)
            for col, pad in ((ad_idx, 0), (etype, -1), (etime, 0),
                             (user_idx, 0), (page_idx, 0), (ad_type, -1)):
                col[:n] = col[keep]
                col[n:] = pad
            valid = np.zeros(B, bool)
            valid[:n] = True
        self.base_time_ms = base = self._lib.sb_encoder_base_time(self._enc)
        if base == BASE_UNSET:
            self.base_time_ms = None
        return EncodedBatch(ad_idx, etype, etime, user_idx, page_idx,
                            ad_type, valid, n=n,
                            base_time_ms=self.base_time_ms
                            if self.base_time_ms is not None else 0), \
            consumed

    def carve_block(self, data: bytes, batch_size: int, start: int = 0,
                    max_batches: int | None = None,
                    end: int | None = None
                    ) -> tuple[list[EncodedBatch], int]:
        """Encode consecutive batches out of a raw block: returns the
        non-empty batches plus the offset where consumption stopped
        (either end-of-complete-records or the ``max_batches`` cap).
        The shared carve loop for every block-mode call site."""
        bound = len(data) if end is None else min(end, len(data))
        batches: list[EncodedBatch] = []
        while ((max_batches is None or len(batches) < max_batches)
               and start < bound):
            b, consumed = self.encode_block(data, batch_size, start, bound)
            if consumed <= 0:
                break
            start += consumed
            if b.n:
                batches.append(b)
        return batches, start

    def _parse_fallback(self, line: bytes):
        try:
            ev = json.loads(line)
            t = int(ev["event_time"])
        except (KeyError, ValueError, TypeError):
            return None
        if self._lib.sb_encoder_base_time(self._enc) == BASE_UNSET:
            self._lib.sb_encoder_set_base_time(
                self._enc,
                t - (t % self.divisor_ms) - self.lateness_ms)
        base = self._lib.sb_encoder_base_time(self._enc)
        if not (-2**31 <= t - base < 2**31):
            # rebased time must fit the int32 column; an absurd timestamp
            # (clock garbage, fuzzed input) is a bad line, not a crash
            return None
        ad = str(ev.get("ad_id", "")).encode()
        u = str(ev.get("user_id", "")).encode()
        p = str(ev.get("page_id", "")).encode()
        if self.hash_ids:
            # the fallback must mirror the fast path's id semantics: an
            # interned index here would be a phantom distinct user to the
            # HLL kernel (and could collide with other users' hashes)
            uid, pid = _id_hash32(u), _id_hash32(p)
        elif self.intern_ids:
            uid = self._lib.sb_intern_user(self._enc, u, len(u))
            pid = self._lib.sb_intern_page(self._enc, p, len(p))
        else:
            # stray fallback rows must not grow the maps or break the
            # zeros invariant when interning is off
            uid = pid = 0
        return (
            self.ad_index.get(ad, self.unknown_ad),
            EVENT_TYPE_INDEX.get(str(ev.get("event_type", "")), -1),
            t - base,
            uid,
            pid,
            AD_TYPE_INDEX.get(str(ev.get("ad_type", "")), -1),
        )


def make_encoder(ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 divisor_ms: int = 10_000, lateness_ms: int = 60_000,
                 use_native: bool = True) -> EventEncoder:
    """Native encoder when available, else the pure-Python one."""
    if use_native and native.load() is not None:
        return NativeEventEncoder(ad_to_campaign, campaigns,
                                  divisor_ms=divisor_ms,
                                  lateness_ms=lateness_ms)
    return EventEncoder(ad_to_campaign, campaigns,
                        divisor_ms=divisor_ms, lateness_ms=lateness_ms)
