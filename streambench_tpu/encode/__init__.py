from streambench_tpu.encode.encoder import (  # noqa: F401
    AD_TYPE_INDEX,
    EVENT_TYPE_INDEX,
    VIEW,
    EncodedBatch,
    EventEncoder,
)
