"""Latency accounting: decile report + stall detection.

Re-expresses the reference's two in-repo observability idioms
(SURVEY.md §5.5):

- the Apex latency-aware store (``ProcessTimeAwareStore.java``): per
  (key, bucket) last-update times recorded as aggregates land
  (``updateUpdateTime``, ``:102-111``), then a final report of sorted
  latencies ``update_time − bucket − window_len`` with the first
  ``ignore_first`` and the trailing bucket dropped as incomplete
  (``logFinalLatencies``, ``:115-146``) and a 10-group percentile table
  (``outputGroupByCount``, ``:160-176``);
- its backpressure stall warning: log when the gap between consecutive
  end-of-window callbacks exceeds 2x the streaming window
  (``:84-87``, 400 ms for the 200 ms window).

Here the "store" is the engine's flush path, so ``LatencyTracker.record``
is called once per window writeback and the report runs at close (or on
demand).  Pure host-side bookkeeping: tiny dicts, no device work.
"""

from __future__ import annotations

import logging
import threading
from collections import defaultdict
from typing import Callable

logger = logging.getLogger("streambench.metrics")


class FaultCounters:
    """Thread-safe monotonic counters for fault/retry/recovery events.

    The reference engines have no fault accounting at all — a Redis
    outage surfaces as a Jedis stack trace and a recount-from-earliest
    restart (PAPER.md §0).  Here every adverse event is counted so a run
    can report *how* it survived, not just that it did:

    - ``sink_errors``       — window writebacks that raised (per batch)
    - ``sink_retries``      — rows re-merged into pending for retry
    - ``sink_reconnects``   — reconnect attempts after a sink error
    - ``sink_dirty_high_water`` — retained-rows cap crossings (warned)
    - ``sink_backoff_ms``   — total writer backoff sleep
    - ``crashes_injected``  — simulated ``EngineCrash``es raised
    - ``restarts``          — supervised restarts performed
    - ``journal_faults``    — injected journal read faults served
    - ``journal_corrupt_skipped`` — torn/NUL records skipped by a reader
    - ``dlq_lines``         — malformed lines shunted to the dead-letter
      journal
    - ``flush_stalls``      — flush-cadence gaps past the stall threshold
      (``StallDetector`` with ``counters`` wired)
    - ``rows_lost``         — window rows abandoned at writer shutdown
      after ``CLOSE_RETRY_LIMIT`` exhausted (counted AND raised: a
      silent-loss run can never report a clean exit)

    Exactly-once mode (``jax.sink.exactly_once``, ROBUSTNESS.md):

    - ``fence_conflicts``   — flushes aborted because a newer writer
      epoch owns the sink (zombie guard)
    - ``dedup_suppressed_flushes`` — failed flushes whose commit fence
      proved they fully landed; the retry was suppressed
    - ``reconciled_windows`` — windows rewritten absolute from the
      cumulative ledger (tainted or reconcile-mode flushes)
    - ``sink_unfenced_resumes`` — resumes that found sink fence state
      past the snapshot's (unfenced flushes -> reconcile mode)
    - ``fence_read_errors`` — sink-fence reads that failed (attach
      retried; reconcile assumed conservatively)

    Writers are the Redis flusher thread, the chaos injector, and the
    supervisor — concurrent by construction, hence the lock.  ``inc`` is
    a dict add under a lock (~100 ns); nothing here is on the device
    path.
    """

    def __init__(self) -> None:
        self._counts: dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] += n

    def get(self, key: str, default: int = 0) -> int:
        """Current count for ``key``; ``default`` for a key never
        bumped (the dict-like signature callers kept reaching for —
        PR 10 shipped without it and call sites had to know the
        zero-default by heart)."""
        with self._lock:
            return self._counts.get(key, default)

    def snapshot(self) -> dict[str, int]:
        """Non-zero counters as a plain dict (RunStats surfacing)."""
        with self._lock:
            return {k: v for k, v in self._counts.items() if v}

    def merge(self, other: "dict[str, int] | FaultCounters") -> None:
        items = (other.snapshot() if isinstance(other, FaultCounters)
                 else other)
        with self._lock:
            for k, v in items.items():
                self._counts[k] += v


class LatencyTracker:
    """Per-(key, bucket) last-update times -> final latency distribution."""

    def __init__(self, window_ms: int = 10_000, ignore_first: int = 10):
        self.window_ms = window_ms
        self.ignore_first = ignore_first
        # bucket(ms, window start) -> key -> last update time (ms)
        self._updates: dict[int, dict[str, int]] = defaultdict(dict)
        # bulk batches (key_idx array, bucket array, stamp, names) parked
        # until a report asks for them: the per-pair dict updates are too
        # slow for catchup flush sizes (10^5 rows) on the hot path
        self._bulk: list = []

    def record(self, key: str, bucket_ms: int, update_time_ms: int) -> None:
        self._merge_bulk()  # keep single/bulk recording order coherent
        self._updates[bucket_ms][key] = update_time_ms

    def record_bulk(self, key_idx, buckets, update_time_ms: int,
                    names: list[str]) -> None:
        """Record a whole flush batch as arrays; merged lazily at report
        time (last update per (bucket, key) wins, append order = time
        order, same as repeated ``record`` calls)."""
        self._bulk.append((key_idx, buckets, int(update_time_ms), names))

    def _merge_bulk(self) -> None:
        if not self._bulk:
            return
        bulk, self._bulk = self._bulk, []
        for key_idx, buckets, stamp, names in bulk:
            for c, b in zip(key_idx.tolist(), buckets.tolist()):
                self._updates[b][names[c]] = stamp

    def final_latencies(self) -> list[int]:
        """Sorted ``update − bucket − window_len`` over complete buckets.

        The first ``ignore_first`` buckets (engine warm-up) and the last
        bucket (still filling when the run stopped) are excluded, exactly
        the reference's trimming (``ProcessTimeAwareStore.java:129-140``).
        Returns [] when too few buckets survive the trim.
        """
        self._merge_bulk()
        buckets = sorted(self._updates)
        if len(buckets) <= self.ignore_first + 1:
            return []
        kept = buckets[self.ignore_first:-1]
        out = [t - b - self.window_ms
               for b in kept for t in self._updates[b].values()]
        out.sort()
        return out

    def decile_table(self) -> list[tuple[str, int]]:
        return decile_table(self.final_latencies())

    def report(self) -> str:
        lats = self.final_latencies()
        if not lats:
            return ("latency report: not enough complete windows "
                    f"({len(self._updates)} buckets, need "
                    f"> {self.ignore_first + 1})")
        lines = [f"latency report over {len(lats)} samples "
                 f"({len(self._updates)} buckets, first {self.ignore_first} "
                 "+ last ignored):"]
        lines += [f"  {rng}: {v} ms" for rng, v in decile_table(lats)]
        return "\n".join(lines)


def decile_table(latencies: list[int]) -> list[tuple[str, int]]:
    """10 equal-count groups; each row is the group's upper-bound latency
    (``outputGroupByCount``: row i = sorted[step*(i+1)], last = max).

    The index is proportional (``n * (i+1) // 10``), not the reference's
    integer ``step = n // 10`` multiple: below 10 samples the truncated
    step is 0 and every row would repeat ``sorted[0]``.  Proportional
    indices are identical when n divides evenly by 10, drift by at most
    the truncation remainder otherwise, and spread small samples across
    the order statistics instead of collapsing them.
    """
    if not latencies:
        return []
    groups = 10
    n = len(latencies)
    rows: list[tuple[str, int]] = []
    for i in range(groups - 1):
        idx = min(n * (i + 1) // groups, n - 1)
        rows.append((f"{i * 100 // groups} - {(i + 1) * 100 // groups}",
                     int(latencies[idx])))
    rows.append((f"{(groups - 1) * 100 // groups} - 100", int(latencies[-1])))
    return rows


class StallDetector:
    """Warn when consecutive progress ticks are too far apart.

    The reference warns on an end-window gap over 2x the streaming window
    (``ProcessTimeAwareStore.java:84-87``).  ``tick()`` is called once per
    flush; returns the gap in ms when it stalled, else None.

    When ``counters`` is given, every stall also bumps its
    ``flush_stalls`` key — routing stalls into the engine's
    ``FaultCounters`` so they surface in ``RunStats.faults`` and the
    telemetry stream next to the sink/chaos counters, not only in a
    log line and this object's own attribute.
    """

    def __init__(self, expected_period_ms: int,
                 factor: float = 2.0,
                 warn: Callable[[str], None] | None = None,
                 counters: "FaultCounters | None" = None):
        self.threshold_ms = expected_period_ms * factor
        self._warn = warn or logger.warning
        self._counters = counters
        self._last_ms: int | None = None
        self.stalls = 0
        # Largest observed gap (ms) across the run: the bench's
        # independent wall-clock stall evidence — its one-shot retry must
        # not fire on the percentile shape alone (ADVICE r5).
        self.max_gap_ms = 0

    def reset(self) -> None:
        """Drop the cadence baseline (engine restart / resumed run): the
        next tick establishes a fresh one instead of billing the
        downtime as a stall."""
        self._last_ms = None

    def tick(self, now_ms: int) -> int | None:
        gap = None
        if self._last_ms is not None:
            period = now_ms - self._last_ms
            if period > self.threshold_ms:
                gap = period
                self.stalls += 1
                self.max_gap_ms = max(self.max_gap_ms, period)
                if self._counters is not None:
                    self._counters.inc("flush_stalls")
                self._warn(
                    f"unexpected long flush period: {period} ms "
                    f"(threshold {self.threshold_ms:.0f} ms)")
        self._last_ms = now_ms
        return gap
