from streambench_tpu.parallel.mesh import build_mesh, mesh_from_config
from streambench_tpu.parallel.sharded import (
    ShardedWindowEngine,
    sharded_init_state,
    sharded_step,
)

__all__ = [
    "build_mesh",
    "mesh_from_config",
    "ShardedWindowEngine",
    "sharded_init_state",
    "sharded_step",
]
