from streambench_tpu.parallel.distributed import (
    DistContext,
    DistributedWindowEngine,
    cross_host_barrier,
    global_mesh,
    init_distributed,
    run_distributed_catchup,
)
from streambench_tpu.parallel.mesh import build_mesh, mesh_from_config
from streambench_tpu.parallel.reach import (
    ShardedReachEngine,
    sharded_reach_init,
)
from streambench_tpu.parallel.sharded import (
    ShardedWindowEngine,
    sharded_init_state,
    sharded_step,
)
from streambench_tpu.parallel.sketches import (
    ShardedHLLEngine,
    ShardedSessionCMSEngine,
    ShardedSlidingTDigestEngine,
    sharded_hll_init,
    sharded_hll_step,
)

__all__ = [
    "DistContext",
    "DistributedWindowEngine",
    "build_mesh",
    "cross_host_barrier",
    "global_mesh",
    "init_distributed",
    "mesh_from_config",
    "run_distributed_catchup",
    "ShardedHLLEngine",
    "ShardedReachEngine",
    "ShardedSessionCMSEngine",
    "ShardedSlidingTDigestEngine",
    "ShardedWindowEngine",
    "sharded_hll_init",
    "sharded_hll_step",
    "sharded_reach_init",
    "sharded_init_state",
    "sharded_step",
]
