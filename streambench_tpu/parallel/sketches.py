"""Sharded sketch engines: the "sharded sketch-merge allreduce" of
BASELINE config #5, realized for every sketch family.

The reference's scale-out routes keyed state to its owner (Storm
``fieldsGrouping("campaign_id")``, ``AdvertisingTopology.java:232-233``)
and merges parallel partials through a unifier
(``ApplicationDimensionComputation.java:120``).  The exact-count engine
already does this with a campaign-sharded ``psum`` (``parallel/sharded.py``);
this module gives the sketches the same treatment, each with its natural
merge reduction (SURVEY.md §2 "Reduce/unifier" row):

- **HLL** (``ShardedHLLEngine``): registers ``[C, W, R]`` sharded on the
  campaign axis.  Register merge is elementwise **max** — but instead of
  pmax-ing register-sized partials (O(C*W*R) bytes over ICI per step),
  the O(B) batch columns are ``all_gather``-ed over the data axis and
  each campaign shard scatter-maxes every event into its own rows.
  Cross-device traffic per step is four [B] int32 columns, independent
  of sketch size; the merge happens implicitly because each campaign's
  registers have exactly one owner.  The only collective reduction is a
  scalar ``psum`` for the drop counter.
- **Session + CMS** (``ShardedSessionCMSEngine``): per-user session rows
  sharded on the *user* axis (the flattened ``data x campaign`` mesh —
  the per-key-sequential state is keyed by user, not campaign, so the
  whole mesh becomes one shard axis, the analog of the fork's
  ``reduce.partitions`` keyed by a different field).  Each shard
  sessionizes only its users; closed sessions fold into per-shard CMS
  *deltas* that merge with **psum** — the sketch-merge allreduce — onto
  a replicated table, and the closed-session rows ``all_gather`` so the
  device-resident heavy-hitter ring updates identically everywhere.

Both engines are drop-in subclasses: same host loop, Redis writeback,
checkpoint format (snapshots gather to host arrays; restore re-places
shardings), and CLI flags as their single-device parents.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.engine.sketches import (
    LAT_BIN_MS,
    LAT_BINS,
    HLLDistinctEngine,
    SessionCMSEngine,
    SlidingTDigestEngine,
    _hist_rows,
)
from streambench_tpu.io.redis_schema import RedisLike
from streambench_tpu.ops import cms, hll, salsa, session, sliding, tdigest
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.ops.windowcount import NEG, WindowState, assign_windows
from streambench_tpu.parallel.mesh import CAMPAIGN_AXIS, DATA_AXIS
from streambench_tpu.parallel.sharded import (
    data_axis_pad,
    pad_campaigns,
    pad_data_cols,
)

try:  # jax >= 0.6 top-level export
    from jax import shard_map as _shard_map_raw
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_raw

MESH_AXES = (DATA_AXIS, CAMPAIGN_AXIS)


def shard_map(body, **kw):
    """``jax.shard_map`` with the static replication check disabled.

    The sketch folds gather the O(B) batch columns (``all_gather``) and
    scatter into shard-local state, so every output is value-replicated
    where its out_spec says — but jax's varying-mesh-axes inference
    treats ``all_gather`` results as varying over the gathered axis and
    cannot prove it.  The alternative (pmax/psum laundering) would move
    O(C*W*R) register bytes over ICI per step, defeating the design.

    With the static check off, the multi-device bit-identity tests
    (``tests/test_sharded_sketches.py``, run on the 8-CPU mesh in CI)
    are the SOLE replication guard for these kernels: an edit that
    breaks output replication will only be caught there, so those tests
    are mandatory for any change to this module.
    """
    try:
        return _shard_map_raw(body, check_vma=False, **kw)
    except TypeError:  # pragma: no cover - older jax spelling
        return _shard_map_raw(body, check_rep=False, **kw)


# ----------------------------------------------------------------------
# Sharded HLL
# ----------------------------------------------------------------------

def _gather_cols(*cols):
    """All-gather data-axis-sharded columns along their LAST axis: the
    per-batch ``[b]`` form and the hoisted-scan ``[K, b]`` stack share
    one spelling (ONE [K, B] collective per column per dispatch)."""
    return tuple(
        jax.lax.all_gather(c, DATA_AXIS, axis=c.ndim - 1, tiled=True)
        for c in cols)


def _hll_fold_local(registers, window_ids, watermark, join_table,
                    ad, user, et, tm, v,
                    *, divisor_ms: int, lateness_ms: int, view_type: int,
                    stats_shards: int = 0):
    """The collective-free HLL fold over already-replicated columns.
    Returns ``(registers, ids, wm, wanted_n, counted_local)``; the
    caller psums ``counted_local`` over the campaign axis — per batch
    (``_hll_fold``) or once per dispatch (the hoisted scan; psum is
    linear over int32 sums, so deferring the merge is bit-identical)."""
    Cl, W, R = registers.shape
    p = R.bit_length() - 1

    campaign = join_table[ad]
    wid = tm // divisor_ms
    wanted = v & (et == view_type) & (campaign >= 0)

    # Windowing core shared with hll.step: the gathered columns are
    # replicated, so the single-device claim/watermark logic computes the
    # same global facts on every device with no further collectives.
    slot, count_mask, new_ids, new_wm = assign_windows(
        window_ids, watermark, wid, wanted, v, tm,
        divisor_ms=divisor_ms, lateness_ms=lateness_ms)

    # Keyed-state routing without moving state: this shard owns campaigns
    # [c0, c0 + Cl); everything else scatters to the drop slot.
    c0 = jax.lax.axis_index(CAMPAIGN_AXIS) * Cl
    local_c = campaign - c0
    in_shard = count_mask & (local_c >= 0) & (local_c < Cl)

    h = hll.splitmix32(user)
    j = (h & jnp.uint32(R - 1)).astype(jnp.int32)
    rank = hll._rank(h, p)

    flat = jnp.where(in_shard, (local_c * W + slot) * R + j, Cl * W * R)
    new_regs = (registers.reshape(-1)
                .at[flat].max(rank.astype(registers.dtype), mode="drop")
                .reshape(Cl, W, R))

    wanted_n = jnp.sum(wanted.astype(jnp.int32))
    counted_local = jnp.sum(in_shard.astype(jnp.int32))
    base = (new_regs, new_ids, new_wm, wanted_n, counted_local)
    if not stats_shards:
        return base
    # per-shard skew stats (obs.xfer.ShardSkew): replicated [S]
    # histograms by owning shard — see parallel.sharded._shard_hist
    from streambench_tpu.parallel.sharded import _shard_hist

    wanted_s = _shard_hist(campaign, wanted, Cl, stats_shards)
    routed_s = _shard_hist(campaign, count_mask, Cl, stats_shards)
    return base + (wanted_s, routed_s)


def _hll_fold(registers, window_ids, watermark, dropped, join_table,
              ad_idx, user_idx, event_type, event_time, valid,
              *, divisor_ms: int, lateness_ms: int, view_type: int,
              stats_shards: int = 0):
    """One batch folded into a campaign shard, written against shard-local
    views inside ``shard_map``.  Batch columns arrive data-sharded and are
    gathered here, so every value derived from them is replicated and the
    ring claim / watermark / drop math needs no further collectives."""
    ad, user, et, tm, v = _gather_cols(ad_idx, user_idx, event_type,
                                       event_time, valid)
    new_regs, new_ids, new_wm, wanted_n, counted_local, *stats = \
        _hll_fold_local(
            registers, window_ids, watermark, join_table,
            ad, user, et, tm, v, divisor_ms=divisor_ms,
            lateness_ms=lateness_ms, view_type=view_type,
            stats_shards=stats_shards)
    counted = jax.lax.psum(counted_local, CAMPAIGN_AXIS)
    new_dropped = dropped + wanted_n - counted
    return (new_regs, new_ids, new_wm, new_dropped) + tuple(stats)


def _hll_fold_packed(registers, window_ids, watermark, dropped, join_table,
                     packed, user_idx, event_time,
                     *, divisor_ms: int, lateness_ms: int, view_type: int,
                     stats_shards: int = 0):
    """``_hll_fold`` consuming the packed wire word: three data-axis
    gathers per batch (packed, user, time) instead of five — the ISSUE 7
    wire packing, extended to the sketch engines.  Unpacks AFTER the
    gather, so every device decodes identical replicated words."""
    pk, user, tm = _gather_cols(packed, user_idx, event_time)
    ad, et, v = wc.unpack_columns(pk)
    new_regs, new_ids, new_wm, wanted_n, counted_local, *stats = \
        _hll_fold_local(
            registers, window_ids, watermark, join_table,
            ad, user, et, tm, v, divisor_ms=divisor_ms,
            lateness_ms=lateness_ms, view_type=view_type,
            stats_shards=stats_shards)
    counted = jax.lax.psum(counted_local, CAMPAIGN_AXIS)
    new_dropped = dropped + wanted_n - counted
    return (new_regs, new_ids, new_wm, new_dropped) + tuple(stats)


@functools.lru_cache(maxsize=None)
def _build_hll_step(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                    view_type: int, stats: bool = False):
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0

    def body(registers, window_ids, watermark, dropped, join_table,
             ad_idx, user_idx, event_type, event_time, valid):
        return _hll_fold(registers, window_ids, watermark, dropped,
                         join_table, ad_idx, user_idx, event_type,
                         event_time, valid, divisor_ms=divisor_ms,
                         lateness_ms=lateness_ms, view_type=view_type,
                         stats_shards=n_stats)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P(), P(),
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _build_hll_step_packed(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                           view_type: int, stats: bool = False):
    """``_build_hll_step`` consuming (packed, user_idx, event_time) wire
    columns: three data-axis gathers per step instead of five."""
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0

    def body(registers, window_ids, watermark, dropped, join_table,
             packed, user_idx, event_time):
        return _hll_fold_packed(registers, window_ids, watermark, dropped,
                                join_table, packed, user_idx, event_time,
                                divisor_ms=divisor_ms,
                                lateness_ms=lateness_ms,
                                view_type=view_type,
                                stats_shards=n_stats)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P(), P(),
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    return jax.jit(mapped)


def _hll_scan_hoisted(join_table, state4, cols, *, divisor_ms: int,
                      lateness_ms: int, view_type: int, packed: bool,
                      stats_shards: int = 0):
    """Shared hoisted-scan core: ``cols`` are ALREADY-GATHERED ``[K, B]``
    stacks; the scan body is collective-free and the drop-counter psum
    merges once after the scan (bit-identical — psum is linear)."""
    registers, window_ids, watermark, dropped = state4

    # Per-batch (wanted, counted_local) ride the scan's ys — see
    # parallel.sharded._build_scan: int32 sums are exact and
    # associative, so summing after the scan and psum-ing ONCE is
    # bit-identical to the per-batch merges.  The shard-skew [S]
    # histograms (stats arm) ride the same ys.
    def one(carry, xs):
        regs, ids, wm = carry
        if packed:
            p, u, t = xs
            a, e, v = wc.unpack_columns(p)
        else:
            a, u, e, t, v = xs
        regs, ids, wm, wn, cl, *st = _hll_fold_local(
            regs, ids, wm, join_table, a, u, e, t, v,
            divisor_ms=divisor_ms, lateness_ms=lateness_ms,
            view_type=view_type, stats_shards=stats_shards)
        return (regs, ids, wm), (wn, cl) + tuple(st)

    (regs, ids, wm), ys = jax.lax.scan(
        one, (registers, window_ids, watermark), cols)
    wn, cl = ys[0], ys[1]
    new_dropped = dropped + jnp.sum(wn) - jax.lax.psum(jnp.sum(cl),
                                                       CAMPAIGN_AXIS)
    out = (regs, ids, wm, new_dropped)
    if stats_shards:
        out += (jnp.sum(ys[2], axis=0), jnp.sum(ys[3], axis=0))
    return out


@functools.lru_cache(maxsize=None)
def _build_hll_scan(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                    view_type: int, hoist: bool = True,
                    stats: bool = False):
    """Scanned sharded HLL: fold ``[K, B]`` stacked batches in one
    dispatch (the catchup hot path, peer of
    ``parallel.sharded._build_scan``).  ``hoist=True`` (the engine
    default) gathers the stacked columns ONCE per dispatch and psums the
    drop counter once after the scan — 6 collectives per dispatch
    instead of K * 6; ``hoist=False`` keeps the per-batch collectives
    (the measured baseline arm and the equivalence oracle in tests)."""
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0
    if stats and not hoist:
        raise ValueError("shard stats ride the hoisted scan only")

    def body_per_batch(registers, window_ids, watermark, dropped,
                       join_table, ad_idx, user_idx, event_type,
                       event_time, valid):
        def one(carry, xs):
            regs, ids, wm, dr = carry
            a, u, e, t, v = xs
            return _hll_fold(regs, ids, wm, dr, join_table, a, u, e, t, v,
                             divisor_ms=divisor_ms,
                             lateness_ms=lateness_ms,
                             view_type=view_type), None

        carry, _ = jax.lax.scan(
            one, (registers, window_ids, watermark, dropped),
            (ad_idx, user_idx, event_type, event_time, valid))
        return carry

    def body_hoisted(registers, window_ids, watermark, dropped,
                     join_table, ad_idx, user_idx, event_type,
                     event_time, valid):
        cols = _gather_cols(ad_idx, user_idx, event_type, event_time,
                            valid)
        return _hll_scan_hoisted(
            join_table, (registers, window_ids, watermark, dropped), cols,
            divisor_ms=divisor_ms, lateness_ms=lateness_ms,
            view_type=view_type, packed=False, stats_shards=n_stats)

    mapped = shard_map(
        body_hoisted if hoist else body_per_batch, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P(), P(),
                  P(None, DATA_AXIS), P(None, DATA_AXIS),
                  P(None, DATA_AXIS), P(None, DATA_AXIS),
                  P(None, DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _build_hll_scan_packed(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                           view_type: int, hoist: bool = True,
                           stats: bool = False):
    """``_build_hll_scan`` over ``[K, B]`` (packed, user_idx, event_time)
    stacks: 3 gathers + 1 psum per dispatch hoisted, K * 4 per-batch."""
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0
    if stats and not hoist:
        raise ValueError("shard stats ride the hoisted scan only")

    def body_per_batch(registers, window_ids, watermark, dropped,
                       join_table, packed, user_idx, event_time):
        def one(carry, xs):
            regs, ids, wm, dr = carry
            p, u, t = xs
            return _hll_fold_packed(regs, ids, wm, dr, join_table,
                                    p, u, t, divisor_ms=divisor_ms,
                                    lateness_ms=lateness_ms,
                                    view_type=view_type), None

        carry, _ = jax.lax.scan(
            one, (registers, window_ids, watermark, dropped),
            (packed, user_idx, event_time))
        return carry

    def body_hoisted(registers, window_ids, watermark, dropped,
                     join_table, packed, user_idx, event_time):
        cols = _gather_cols(packed, user_idx, event_time)
        return _hll_scan_hoisted(
            join_table, (registers, window_ids, watermark, dropped), cols,
            divisor_ms=divisor_ms, lateness_ms=lateness_ms,
            view_type=view_type, packed=True, stats_shards=n_stats)

    mapped = shard_map(
        body_hoisted if hoist else body_per_batch, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P(), P(),
                  P(None, DATA_AXIS), P(None, DATA_AXIS),
                  P(None, DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    return jax.jit(mapped)


def sharded_hll_step(mesh: Mesh, state: hll.HLLState, join_table,
                     ad_idx, user_idx, event_type, event_time, valid,
                     *, divisor_ms: int = 10_000, lateness_ms: int = 60_000,
                     view_type: int = 0) -> hll.HLLState:
    """Fold one global micro-batch into campaign-sharded HLL state."""
    fn = _build_hll_step(mesh, divisor_ms, lateness_ms, view_type)
    regs, ids, wm, dropped = fn(
        state.registers, state.window_ids, state.watermark, state.dropped,
        join_table, ad_idx, user_idx, event_type, event_time, valid)
    return hll.HLLState(regs, ids, wm, dropped)


def sharded_hll_init(num_campaigns: int, window_slots: int, mesh: Mesh,
                     num_registers: int = 128) -> hll.HLLState:
    if num_registers & (num_registers - 1):
        raise ValueError("num_registers must be a power of two")
    C = pad_campaigns(num_campaigns, mesh)
    rep = NamedSharding(mesh, P())
    return hll.HLLState(
        registers=jax.device_put(
            jnp.zeros((C, window_slots, num_registers), jnp.uint8),
            NamedSharding(mesh, P(CAMPAIGN_AXIS, None, None))),
        window_ids=jax.device_put(
            jnp.full((window_slots,), -1, jnp.int32), rep),
        watermark=jax.device_put(jnp.int32(0), rep),
        dropped=jax.device_put(jnp.int32(0), rep),
    )


class ShardedHLLEngine(HLLDistinctEngine):
    """HLL distinct-user engine with registers sharded on the campaign
    axis of a ``(data, campaign)`` mesh.

    Config #5's multi-tenant scale (1e6 campaigns) makes replicated
    registers impossible — ``[1e6, W, R]`` int32 is GBs; one campaign
    shard per device is how it fits, exactly as the exact-count engine
    shards its ``[C, W]`` counts.  The flush path
    (``hll.flush``: estimate + zero closed slots) is elementwise over the
    campaign axis, so it runs on the sharded registers under plain jit
    with XLA propagating the sharding — no gather until the host reads
    the [C, W] estimates of closed windows.
    """

    # Unlike the single-device sketch step, the sharded HLL step DOES
    # pack the wire word when eligible (_build_hll_step_packed) — keeps
    # the transfer ledger's per-format attribution honest.
    STEP_PACKS = True

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 mesh: Mesh, campaigns: list[str] | None = None,
                 redis: RedisLike | None = None, registers: int = 128,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, registers=registers,
                         input_format=input_format)
        self.mesh = mesh
        # Non-divisible batch sizes pad with invalid rows at dispatch,
        # exactly like the exact-count engine (parallel.sharded).
        self._data_pad = data_axis_pad(self.batch_size, mesh)
        self.state = sharded_hll_init(
            self.encoder.num_campaigns, self.W, mesh,
            num_registers=registers)
        self.join_table = jax.device_put(
            jnp.asarray(self.encoder.join_table),
            NamedSharding(mesh, P()))

    def _stats_on(self) -> bool:
        """Shard-skew stats arm (jax.obs.shard) — see
        ``ShardedWindowEngine._stats_on``: separate compiled programs,
        default output byte-identical."""
        return self._obs_shard is not None

    def _note_shard(self, out) -> tuple:
        if self._obs_shard is None:
            return out
        self._obs_shard.note(out[-2], out[-1])
        return out[:-2]

    def _device_step(self, batch) -> None:
        stats = self._stats_on()
        if self._pack_ok:
            fn = _build_hll_step_packed(self.mesh, self.divisor,
                                        self.lateness, 0, stats)
            packed = wc.pack_columns(batch.ad_idx, batch.event_type,
                                     batch.valid)
            packed, user, tm = pad_data_cols(
                self._data_pad, packed, batch.user_idx, batch.event_time)
            regs, ids, wm, dropped = self._note_shard(fn(
                self.state.registers, self.state.window_ids,
                self.state.watermark, self.state.dropped, self.join_table,
                packed, user, tm))
            self.state = hll.HLLState(regs, ids, wm, dropped)
            return
        ad, user, et, tm, va = pad_data_cols(
            self._data_pad, batch.ad_idx, batch.user_idx,
            batch.event_type, batch.event_time, batch.valid)
        if stats:
            fn = _build_hll_step(self.mesh, self.divisor, self.lateness,
                                 0, True)
            regs, ids, wm, dropped = self._note_shard(fn(
                self.state.registers, self.state.window_ids,
                self.state.watermark, self.state.dropped,
                self.join_table, ad, user, et, tm, va))
            self.state = hll.HLLState(regs, ids, wm, dropped)
            return
        self.state = sharded_hll_step(
            self.mesh, self.state, self.join_table, ad, user, et, tm, va,
            divisor_ms=self.divisor, lateness_ms=self.lateness)

    def _device_scan(self, ad_idx, user_idx, event_type, event_time,
                     valid) -> None:
        fn = _build_hll_scan(self.mesh, self.divisor, self.lateness, 0,
                             True, self._stats_on())
        ad_idx, user_idx, event_type, event_time, valid = pad_data_cols(
            self._data_pad, ad_idx, user_idx, event_type, event_time,
            valid)
        regs, ids, wm, dropped = self._note_shard(fn(
            self.state.registers, self.state.window_ids,
            self.state.watermark, self.state.dropped, self.join_table,
            ad_idx, user_idx, event_type, event_time, valid))
        self.state = hll.HLLState(regs, ids, wm, dropped)

    def _device_scan_packed(self, packed, user_idx, event_time) -> None:
        """The packed wire word, extended to the sharded sketch engine
        (ISSUE 7): 3 stacked columns gather per dispatch instead of 5."""
        fn = _build_hll_scan_packed(self.mesh, self.divisor,
                                    self.lateness, 0, True,
                                    self._stats_on())
        packed, user_idx, event_time = pad_data_cols(
            self._data_pad, packed, user_idx, event_time)
        regs, ids, wm, dropped = self._note_shard(fn(
            self.state.registers, self.state.window_ids,
            self.state.watermark, self.state.dropped, self.join_table,
            packed, user_idx, event_time))
        self.state = hll.HLLState(regs, ids, wm, dropped)

    def attach_obs(self, registry, lifecycle: bool = False,
                   spans=None, occupancy=None, xfer=None,
                   shard=None) -> None:
        super().attach_obs(registry, lifecycle, spans=spans,
                           occupancy=occupancy, xfer=xfer, shard=shard)
        self._obs_reg = registry

    def collective_report(self, k: int | None = None) -> dict:
        """Per-dispatch collective costs of the compiled HLL kernels
        (see ``ShardedWindowEngine.collective_report``)."""
        from streambench_tpu.parallel import collectives

        k = int(k or self.scan_batches)
        B = self.batch_size + self._data_pad
        st = self.state
        state_args = (st.registers, st.window_ids, st.watermark,
                      st.dropped, self.join_table)
        zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
        if self._pack_ok:
            step_fn = _build_hll_step_packed(self.mesh, self.divisor,
                                             self.lateness, 0)
            step_args = (zi(B), zi(B), zi(B))
            scan_fn = _build_hll_scan_packed(self.mesh, self.divisor,
                                             self.lateness, 0)
            scan_args = (zi(k, B), zi(k, B), zi(k, B))
        else:
            step_fn = _build_hll_step(self.mesh, self.divisor,
                                      self.lateness, 0)
            step_args = (zi(B), zi(B), zi(B), zi(B),
                         jnp.zeros((B,), bool))
            scan_fn = _build_hll_scan(self.mesh, self.divisor,
                                      self.lateness, 0)
            scan_args = (zi(k, B), zi(k, B), zi(k, B), zi(k, B),
                         jnp.zeros((k, B), bool))
        report = {
            "batch_events": self.batch_size,
            "scan_batches": k,
            "packed": bool(self._pack_ok),
            "step": collectives.report_for(step_fn, *state_args,
                                           *step_args),
            "scan": collectives.report_for(scan_fn, *state_args,
                                           *scan_args, scan_len=k),
        }
        reg = getattr(self, "_obs_reg", None)
        if reg is not None:
            collectives.publish_gauges(reg, report)
        return report

    def restore(self, snap) -> None:
        super().restore(snap)
        # Re-place host-restored state with mesh shardings (accepting a
        # snapshot from an unsharded HLL engine by padding campaigns).
        C = pad_campaigns(self.encoder.num_campaigns, self.mesh)
        regs = np.asarray(self.state.registers)
        if regs.shape[0] < C:
            regs = np.pad(regs, ((0, C - regs.shape[0]), (0, 0), (0, 0)))
        rep = NamedSharding(self.mesh, P())
        self.state = hll.HLLState(
            registers=jax.device_put(
                jnp.asarray(regs),
                NamedSharding(self.mesh, P(CAMPAIGN_AXIS, None, None))),
            window_ids=jax.device_put(
                jnp.asarray(self.state.window_ids), rep),
            watermark=jax.device_put(
                jnp.int32(self.state.watermark), rep),
            dropped=jax.device_put(jnp.int32(self.state.dropped), rep),
        )


# ----------------------------------------------------------------------
# Sharded sliding windows + t-digest
# ----------------------------------------------------------------------

def _sliding_digest_local(means, weights, now_rel, local_c, tm, dmask,
                          Cl, hist):
    """The shared digest half of every sharded sliding fold: step form
    compresses the batch into the digest, scan form folds the O(B)
    histogram only (one absorb per chunk)."""
    lat = jnp.maximum(now_rel - tm, 0)
    if hist is None:
        dg = tdigest.update(
            tdigest.TDigestState(means, weights), local_c, lat, dmask)
        return dg.means, dg.weights, None
    w = jnp.where(dmask, 1.0, 0.0).astype(jnp.float32)
    hn, hw = tdigest.fold_hist(hist[0], hist[1], local_c, lat, w, Cl)
    return means, weights, (hn, hw)


def _sliding_td_fold_local(counts, window_ids, watermark, means, weights,
                           join_table, now_rel, ad, et, tm, v,
                           *, size_ms: int, slide_ms: int,
                           lateness_ms: int, view_type: int, hist=None):
    """The collective-free legacy (unrolled per-k) sliding fold over
    ALREADY-REPLICATED columns: S sliding memberships into the counts
    ring + latency samples into the shard's t-digests.

    Each campaign shard folds the full batch masked to its own
    campaigns — the digest "merge" is OWNERSHIP (every campaign's
    digest has exactly one writer), the same unifier-by-routing as the
    exact engine's psum-free counts
    (``ApplicationDimensionComputation.java:120`` is the reference's
    explicit-unifier analog); ``ops.tdigest.merge`` remains the
    explicit union for offline digest joins.  Mirrors
    ``ops.sliding.step`` + ``SlidingTDigestEngine._device_step``
    semantics exactly (within-key ranks are key-local, so shard-local
    folding is bit-compatible with the single-device digest up to
    float-add ordering inside a centroid).  Returns ``counted_local``
    for the caller to psum — per batch (``_sliding_td_fold``) or once
    per dispatch (the hoisted scan; psum is linear over int32 sums, so
    deferring the merge is bit-identical).
    """
    Cl, W = counts.shape
    S = size_ms // slide_ms
    late_eff = sliding.effective_lateness(size_ms, slide_ms, lateness_ms)

    campaign = join_table[ad]
    base_wid = tm // slide_ms
    wanted = v & (et == view_type) & (campaign >= 0)
    c0 = jax.lax.axis_index(CAMPAIGN_AXIS) * Cl
    local_c = campaign - c0
    shard_mask = (local_c >= 0) & (local_c < Cl)
    wanted_n = jnp.sum(wanted.astype(jnp.int32))

    ids = window_ids
    new_wm = watermark
    counted_acc = jnp.int32(0)
    for k in range(S):
        wid = base_wid - k
        slot, count_mask, ids, new_wm = assign_windows(
            ids, watermark, wid, wanted, v, tm,
            divisor_ms=slide_ms, lateness_ms=late_eff)
        in_shard = count_mask & shard_mask
        flat = jnp.where(in_shard, local_c * W + slot, Cl * W)
        counts = (counts.reshape(-1)
                  .at[flat].add(1, mode="drop")
                  .reshape(Cl, W))
        counted_acc = counted_acc + jnp.sum(in_shard.astype(jnp.int32))

    means, weights, hist = _sliding_digest_local(
        means, weights, now_rel, local_c, tm, wanted & shard_mask, Cl,
        hist)
    out = (counts, ids, new_wm, wanted_n, counted_acc, means, weights)
    return out if hist is None else out + (hist,)


def _sliding_sliced_fold_local(counts, window_ids, watermark, means,
                               weights, join_table, now_rel, ad, et, tm,
                               v, *, size_ms: int, slide_ms: int,
                               lateness_ms: int, view_type: int,
                               hist=None):
    """The SLICED sharded sliding fold (ISSUE 12) over already-replicated
    columns: one ring claim on per-slide buckets + one scatter into the
    campaign shard's ``[Cl, S, W]`` lateness-class plane — the sharded
    form of ``ops.sliding.step_sliced_core`` (same dropped conversion:
    an accepted event owns d+1 memberships, counted on its owner shard
    only, so the deferred psum reproduces the single-device counter)."""
    Cl, S, W = counts.shape
    late_eff = sliding.effective_lateness(size_ms, slide_ms, lateness_ms)

    campaign = join_table[ad]
    bid = tm // slide_ms
    wanted = v & (et == view_type) & (campaign >= 0)
    c0 = jax.lax.axis_index(CAMPAIGN_AXIS) * Cl
    local_c = campaign - c0
    shard_mask = (local_c >= 0) & (local_c < Cl)
    wanted_n = jnp.sum(wanted.astype(jnp.int32))

    slot, count_mask, ids, new_wm = assign_windows(
        window_ids, watermark, bid, wanted, v, tm,
        divisor_ms=slide_ms, lateness_ms=late_eff)
    min_open = jnp.maximum((watermark - late_eff) // slide_ms, 0)
    d = jnp.clip(bid - min_open, 0, S - 1)
    in_shard = count_mask & shard_mask
    flat = jnp.where(in_shard, (local_c * S + d) * W + slot, Cl * S * W)
    counts = (counts.reshape(-1)
              .at[flat].add(1, mode="drop")
              .reshape(Cl, S, W))
    counted_acc = jnp.sum(jnp.where(in_shard, d + 1, 0))

    means, weights, hist = _sliding_digest_local(
        means, weights, now_rel, local_c, tm, wanted & shard_mask, Cl,
        hist)
    out = (counts, ids, new_wm, wanted_n, counted_acc, means, weights)
    return out if hist is None else out + (hist,)


def _sliding_td_fold(counts, window_ids, watermark, dropped, means,
                     weights, join_table, now_rel,
                     ad_idx, event_type, event_time, valid,
                     *, size_ms: int, slide_ms: int, lateness_ms: int,
                     view_type: int, sliced: bool = False, hist=None):
    """One batch folded into a campaign shard: gather the data-sharded
    columns, run the (legacy or sliced) local fold, psum the membership
    counter — the per-batch collective arm."""
    S = size_ms // slide_ms
    ad, et, tm, v = _gather_cols(ad_idx, event_type, event_time, valid)
    fold = (_sliding_sliced_fold_local if sliced
            else _sliding_td_fold_local)
    counts, ids, new_wm, wanted_n, counted, means, weights, *h = fold(
        counts, window_ids, watermark, means, weights, join_table,
        now_rel, ad, et, tm, v, size_ms=size_ms, slide_ms=slide_ms,
        lateness_ms=lateness_ms, view_type=view_type, hist=hist)
    # ONE scalar psum for all S memberships (psum is linear; per-slot
    # psums would put S collectives on the hot path for the same result)
    dropped = dropped + S * wanted_n - jax.lax.psum(counted,
                                                    CAMPAIGN_AXIS)
    out = (counts, ids, new_wm, dropped, means, weights)
    return out if hist is None else out + tuple(h)


_SLIDING_STATE_SPECS = (P(CAMPAIGN_AXIS, None), P(), P(), P(),
                        P(CAMPAIGN_AXIS, None), P(CAMPAIGN_AXIS, None))
# sliced counts carry the [Cl, S, W] lateness-class plane
_SLICED_STATE_SPECS = (P(CAMPAIGN_AXIS, None, None), P(), P(), P(),
                       P(CAMPAIGN_AXIS, None), P(CAMPAIGN_AXIS, None))


@functools.lru_cache(maxsize=None)
def _build_sliding_step(mesh: Mesh, size_ms: int, slide_ms: int,
                        lateness_ms: int, view_type: int = 0,
                        sliced: bool = False):
    def body(counts, ids, wm, dr, means, weights, join_table, now_rel,
             ad_idx, event_type, event_time, valid):
        return _sliding_td_fold(
            counts, ids, wm, dr, means, weights, join_table, now_rel,
            ad_idx, event_type, event_time, valid, size_ms=size_ms,
            slide_ms=slide_ms, lateness_ms=lateness_ms,
            view_type=view_type, sliced=sliced)

    state_specs = _SLICED_STATE_SPECS if sliced else _SLIDING_STATE_SPECS
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=state_specs + (P(), P(), P(DATA_AXIS),
                                P(DATA_AXIS), P(DATA_AXIS),
                                P(DATA_AXIS)),
        out_specs=state_specs,
    )
    return jax.jit(mapped, donate_argnums=(0, 4, 5))


@functools.lru_cache(maxsize=None)
def _build_sliding_scan(mesh: Mesh, size_ms: int, slide_ms: int,
                        lateness_ms: int, view_type: int = 0,
                        hoist: bool = True, sliced: bool = False):
    """Scanned sharded sliding+t-digest: fold ``[K, B]`` stacked batches
    in one dispatch (the catchup hot path, peer of
    ``engine.sketches._sliding_tdigest_scan``).  ``hoist=True`` (the
    engine default) gathers the stacked columns ONCE per dispatch and
    psums the membership counter once after the scan — 5 collectives
    per dispatch instead of K * 5 (the PR 7 treatment, extended to the
    sliding family); ``hoist=False`` keeps the per-batch collectives as
    the measured baseline arm and equivalence oracle.  ``sliced=True``
    scans the one-claim-one-scatter fold over the [Cl, S, W] plane."""
    S = size_ms // slide_ms
    fold_local = (_sliding_sliced_fold_local if sliced
                  else _sliding_td_fold_local)

    def body_per_batch(counts, ids, wm, dr, means, weights, join_table,
                       now_rel, ad_idx, event_type, event_time, valid):
        Cl = counts.shape[0]

        def one(carry, xs):
            c, i, w_, d, hn, hw = carry
            a, e, t, v = xs
            c, i, w_, d, _, _, (hn, hw) = _sliding_td_fold(
                c, i, w_, d, means, weights, join_table, now_rel,
                a, e, t, v, size_ms=size_ms, slide_ms=slide_ms,
                lateness_ms=lateness_ms, view_type=view_type,
                sliced=sliced, hist=(hn, hw))
            return (c, i, w_, d, hn, hw), None

        (c, i, w_, d, hn, hw), _ = jax.lax.scan(
            one, (counts, ids, wm, dr) + tdigest.hist_init(Cl),
            (ad_idx, event_type, event_time, valid))
        # one compress per chunk: the scan body stays O(B) scatters
        dg = tdigest.absorb_hist(
            tdigest.TDigestState(means, weights), hn, hw)
        return c, i, w_, d, dg.means, dg.weights

    def body_hoisted(counts, ids, wm, dr, means, weights, join_table,
                     now_rel, ad_idx, event_type, event_time, valid):
        Cl = counts.shape[0]
        cols = _gather_cols(ad_idx, event_type, event_time, valid)

        def one(carry, xs):
            c, i, w_, hn, hw = carry
            a, e, t, v = xs
            c, i, w_, wn, cl, _, _, (hn, hw) = fold_local(
                c, i, w_, means, weights, join_table, now_rel,
                a, e, t, v, size_ms=size_ms, slide_ms=slide_ms,
                lateness_ms=lateness_ms, view_type=view_type,
                hist=(hn, hw))
            return (c, i, w_, hn, hw), (wn, cl)

        (c, i, w_, hn, hw), (wns, cls) = jax.lax.scan(
            one, (counts, ids, wm) + tdigest.hist_init(Cl),
            cols)
        # deferred membership merge: ONE psum per dispatch (linear over
        # the int32 per-batch sums, bit-identical to per-batch merges)
        d = dr + S * jnp.sum(wns) - jax.lax.psum(jnp.sum(cls),
                                                 CAMPAIGN_AXIS)
        dg = tdigest.absorb_hist(
            tdigest.TDigestState(means, weights), hn, hw)
        return c, i, w_, d, dg.means, dg.weights

    state_specs = _SLICED_STATE_SPECS if sliced else _SLIDING_STATE_SPECS
    mapped = shard_map(
        body_hoisted if hoist else body_per_batch, mesh=mesh,
        in_specs=state_specs + (P(), P(), P(None, DATA_AXIS),
                                P(None, DATA_AXIS),
                                P(None, DATA_AXIS),
                                P(None, DATA_AXIS)),
        out_specs=state_specs,
    )
    return jax.jit(mapped, donate_argnums=(0, 4, 5))


class ShardedSlidingTDigestEngine(SlidingTDigestEngine):
    """Sliding-window counts + per-campaign latency t-digest with both
    the counts ring and the digests sharded on the campaign axis.

    The last sketch family's mesh form (VERDICT r4 missing #2): counts
    merge exactly as the exact engine's (ownership + in-place scatter);
    digests merge by ownership — each campaign's centroids live on one
    shard, so the cross-partition "unifier" is the batch all_gather, and
    reading quantiles gathers the [C, K] centroid block to the host.
    Drop-in: same host loop, Redis writeback, checkpoint format
    (snapshots gather to host arrays; restore re-places shardings).
    """

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 mesh: Mesh, campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 size_ms: int | None = None, slide_ms: int = 1_000,
                 window_slots: int | None = None, compression: int = 64,
                 sliced: str | None = None,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, size_ms=size_ms, slide_ms=slide_ms,
                         window_slots=window_slots, compression=compression,
                         sliced=sliced, input_format=input_format)
        self.mesh = mesh
        # Non-divisible batch sizes pad with invalid rows at dispatch,
        # exactly like the exact-count engine (parallel.sharded).
        self._data_pad = data_axis_pad(self.batch_size, mesh)
        self._place_sliding()

    def _place_sliding(self) -> None:
        """(Re-)apply mesh shardings, padding the campaign axis."""
        C = pad_campaigns(self.encoder.num_campaigns, self.mesh)
        rep = NamedSharding(self.mesh, P())
        cshard = NamedSharding(self.mesh, P(CAMPAIGN_AXIS, None))

        def pad_rows(a):
            a = np.asarray(a)
            if a.shape[0] < C:
                a = np.pad(a, ((0, C - a.shape[0]),) + ((0, 0),) *
                           (a.ndim - 1))
            return a

        state_cls = type(self.state)
        counts_sharding = (NamedSharding(
            self.mesh, P(CAMPAIGN_AXIS, None, None))
            if self.sliced else cshard)
        self.state = state_cls(
            counts=jax.device_put(jnp.asarray(pad_rows(self.state.counts)),
                                  counts_sharding),
            window_ids=jax.device_put(
                jnp.asarray(np.asarray(self.state.window_ids)), rep),
            watermark=jax.device_put(jnp.int32(self.state.watermark), rep),
            dropped=jax.device_put(jnp.int32(self.state.dropped), rep))
        self.digest = tdigest.TDigestState(
            means=jax.device_put(jnp.asarray(pad_rows(self.digest.means)),
                                 cshard),
            weights=jax.device_put(
                jnp.asarray(pad_rows(self.digest.weights)), cshard))
        self.join_table = jax.device_put(
            jnp.asarray(self.encoder.join_table), rep)

    def _carry(self):
        return (self.state.counts, self.state.window_ids,
                self.state.watermark, self.state.dropped,
                self.digest.means, self.digest.weights)

    def _uncarry(self, out) -> None:
        counts, ids, wm, dr, means, weights = out
        state_cls = type(self.state)
        self.state = state_cls(counts, ids, wm, dr)
        self.digest = tdigest.TDigestState(means, weights)

    def _device_step(self, batch) -> None:
        fn = _build_sliding_step(self.mesh, self.size_ms, self.slide_ms,
                                 self.base_lateness, 0, self.sliced)
        cols = pad_data_cols(self._data_pad, batch.ad_idx,
                             batch.event_type, batch.event_time,
                             batch.valid)
        self._uncarry(fn(*self._carry(), self.join_table, self._now_rel(),
                         *cols))

    def _device_scan(self, ad_idx, event_type, event_time, valid) -> None:
        fn = _build_sliding_scan(self.mesh, self.size_ms, self.slide_ms,
                                 self.base_lateness, 0, True, self.sliced)
        cols = pad_data_cols(self._data_pad, ad_idx, event_type,
                             event_time, valid)
        self._uncarry(fn(*self._carry(), self.join_table, self._now_rel(),
                         *cols))

    def attach_obs(self, registry, lifecycle: bool = False,
                   spans=None, occupancy=None, xfer=None,
                   shard=None) -> None:
        super().attach_obs(registry, lifecycle, spans=spans,
                           occupancy=occupancy, xfer=xfer, shard=shard)
        self._obs_reg = registry

    def collective_report(self, k: int | None = None) -> dict:
        """Per-dispatch collective costs of the compiled sliding kernels
        (see ``ShardedWindowEngine.collective_report``): the ISSUE 12
        HLO-measured number for the hoisted sliding scan."""
        from streambench_tpu.parallel import collectives

        k = int(k or self.scan_batches)
        B = self.batch_size + self._data_pad
        zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
        carry = self._carry()
        now = jnp.int32(0)
        step_fn = _build_sliding_step(self.mesh, self.size_ms,
                                      self.slide_ms, self.base_lateness,
                                      0, self.sliced)
        scan_fn = _build_sliding_scan(self.mesh, self.size_ms,
                                      self.slide_ms, self.base_lateness,
                                      0, True, self.sliced)
        report = {
            "batch_events": self.batch_size,
            "scan_batches": k,
            "sliced": bool(self.sliced),
            "step": collectives.report_for(
                step_fn, *carry, self.join_table, now, zi(B), zi(B),
                zi(B), jnp.zeros((B,), bool)),
            "scan": collectives.report_for(
                scan_fn, *carry, self.join_table, now, zi(k, B),
                zi(k, B), zi(k, B), jnp.zeros((k, B), bool),
                scan_len=k),
        }
        reg = getattr(self, "_obs_reg", None)
        if reg is not None:
            collectives.publish_gauges(reg, report)
        return report

    def quantiles(self) -> np.ndarray:
        # padded campaign rows are empty digests; slice them off
        q = super().quantiles()
        return q[:self.encoder.num_campaigns]

    def restore(self, snap) -> None:
        super().restore(snap)
        self._place_sliding()


# ----------------------------------------------------------------------
# Sharded session windows + CMS heavy hitters
# ----------------------------------------------------------------------

def _shard_index():
    """Linearized shard id over the flattened (data, campaign) mesh."""
    # jax.lax.axis_size is missing from older jax releases; psum(1) over
    # the named axis is the portable spelling of the same quantity
    axis_size = getattr(jax.lax, "axis_size", None)
    nc = (axis_size(CAMPAIGN_AXIS) if axis_size is not None
          else jax.lax.psum(1, CAMPAIGN_AXIS))
    return jax.lax.axis_index(DATA_AXIS) * nc + jax.lax.axis_index(
        CAMPAIGN_AXIS)


def _globalize(closed: session.ClosedSessions, u0) -> session.ClosedSessions:
    return closed._replace(
        user=jnp.where(closed.valid, closed.user + u0, -1))


def _gather_closed(closed: session.ClosedSessions) -> session.ClosedSessions:
    g = functools.partial(jax.lax.all_gather, axis_name=MESH_AXES,
                          tiled=True)
    return session.ClosedSessions(
        user=g(closed.user), start=g(closed.start), end=g(closed.end),
        clicks=g(closed.clicks), valid=g(closed.valid))


def _cms_delta_psum(shape, closed: session.ClosedSessions):
    """Per-shard CMS delta from closed sessions, psum-merged over the
    whole mesh — the sketch-merge allreduce (counter add is linear, so
    summing per-shard deltas == folding every closed session into one
    table)."""
    zero = cms.CMSState(table=jnp.zeros(shape, jnp.int32),
                        total=jnp.int32(0))
    local = cms.update(zero, closed.user, closed.clicks, closed.valid)
    return (jax.lax.psum(local.table, MESH_AXES),
            jax.lax.psum(local.total, MESH_AXES))


def _session_fold(last_time, sess_start, clicks, watermark, dropped,
                  cms_table, cms_total, tk_keys, tk_ests, closed_n,
                  clicks_n, lat_hist, now_rel,
                  user_idx, event_type, event_time, valid,
                  *, gap_ms: int, lateness_ms: int, user_capacity: int):
    """One batch folded into a user shard + the replicated CMS/ring.

    Batch columns are replicated (every shard sees every event and masks
    to its users — the keyed shuffle without moving state).  Mirrors
    ``SessionCMSEngine._device_step``'s absorb order exactly: CMS delta
    and ring update for in-batch closures first, then for carried
    closures, so estimates in the ring match the single-device engine
    bit for bit.
    """
    Ul = last_time.shape[0]
    u0 = _shard_index() * Ul
    lu = user_idx - u0
    in_shard = (lu >= 0) & (lu < Ul)
    v = valid & in_shard

    local = session.SessionState(last_time, sess_start, clicks,
                                 watermark, jnp.int32(0))
    st, closed_in, closed_carry = session.step(
        local, jnp.where(v, lu, -1), event_type, event_time, v,
        gap_ms=gap_ms, lateness_ms=lateness_ms)

    # Watermark / drop accounting are GLOBAL facts recomputed from the
    # replicated batch (the local step only saw this shard's events):
    # an event is dropped iff late vs the batch-start watermark or its
    # user id is outside the global capacity.
    batch_max = jnp.max(jnp.where(valid, event_time, NEG))
    new_wm = jnp.maximum(watermark, batch_max)
    min_t = watermark - lateness_ms
    ok = (valid & (event_time >= min_t) & (user_idx >= 0)
          & (user_idx < user_capacity))
    new_dropped = dropped + jnp.sum(valid.astype(jnp.int32)) \
        - jnp.sum(ok.astype(jnp.int32))

    cms_state = cms.CMSState(cms_table, cms_total)
    topk = cms.TopKState(tk_keys, tk_ests)
    # closures determined by this batch's evidence (see
    # engine.sketches.SessionCMSEngine._device_step): one shared latency
    # per batch; per-shard closure counts psum into the replicated
    # histogram alongside the counters.
    det_lat = jnp.maximum(
        now_rel - jnp.max(jnp.where(valid, event_time, NEG)), 0)
    det_bin = jnp.clip(det_lat // LAT_BIN_MS, 0, LAT_BINS - 1)
    for closed in (_globalize(closed_in, u0), _globalize(closed_carry, u0)):
        dt, dn = _cms_delta_psum(cms_table.shape, closed)
        cms_state = cms.CMSState(cms_state.table + dt,
                                 cms_state.total + dn)
        gathered = _gather_closed(closed)
        topk = cms.update_topk(cms_state, topk, gathered.user,
                               gathered.valid)
        n_closed = jax.lax.psum(
            jnp.sum(closed.valid.astype(jnp.int32)), MESH_AXES)
        closed_n = closed_n + n_closed
        lat_hist = lat_hist.at[det_bin].add(n_closed)
        clicks_n = clicks_n + jax.lax.psum(
            jnp.sum(jnp.where(closed.valid, closed.clicks, 0)), MESH_AXES)

    return (st.last_time, st.sess_start, st.clicks, new_wm, new_dropped,
            cms_state.table, cms_state.total, topk.keys, topk.ests,
            closed_n, clicks_n, lat_hist)


_SESS_STATE_SPECS = (P(MESH_AXES), P(MESH_AXES), P(MESH_AXES), P(), P(),
                     P(), P(), P(), P(), P(), P(), P())


@functools.lru_cache(maxsize=None)
def _build_session_step(mesh: Mesh, gap_ms: int, lateness_ms: int,
                        user_capacity: int):
    def body(*args):
        return _session_fold(*args, gap_ms=gap_ms,
                             lateness_ms=lateness_ms,
                             user_capacity=user_capacity)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=_SESS_STATE_SPECS + (P(), P(), P(), P(), P()),
        out_specs=_SESS_STATE_SPECS,
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _build_session_scan(mesh: Mesh, gap_ms: int, lateness_ms: int,
                        user_capacity: int, hoist: bool = True):
    """Scanned sharded session+CMS: the whole config-#4 pipeline over
    ``[K, B]`` stacked batches in one dispatch (peer of
    ``engine.sketches._session_cms_scan``).

    ``hoist=False`` keeps the collectives inside the scan body — per
    batch, per closure group: a CMS-delta psum, five closed-row
    all_gathers for the candidate ring, and the counter psums, i.e.
    ~K * 16 collectives per dispatch.  ``hoist=True`` (the engine
    default, the PR 7 treatment extended to the session family) makes
    the scan body collective-free: each batch's per-shard CMS deltas,
    closed rows, and counters ride the scan ys, merge in ONE stacked
    psum / all_gather each after the scan (int adds are linear; the
    gathered row order per (batch, closure) slice is identical), and a
    collective-free replay loop then applies the 2K candidate-ring
    updates against the same evolving CMS prefix states the per-batch
    arm saw — bit-identical output, ~4 collectives per dispatch.
    """

    def body_per_batch(lt, ss, ck, wm, dr, table, total, tkk, tke, cn,
                       cl, hist, now_rel, user_idx, event_type,
                       event_time, valid):
        def one(carry, xs):
            u, e, t, v = xs
            return _session_fold(*carry, now_rel, u, e, t, v,
                                 gap_ms=gap_ms,
                                 lateness_ms=lateness_ms,
                                 user_capacity=user_capacity), None

        carry, _ = jax.lax.scan(
            one, (lt, ss, ck, wm, dr, table, total, tkk, tke, cn, cl,
                  hist),
            (user_idx, event_type, event_time, valid))
        return carry

    def body_hoisted(lt, ss, ck, wm, dr, table, total, tkk, tke, cn,
                     cl, hist, now_rel, user_idx, event_type,
                     event_time, valid):
        Ul = lt.shape[0]
        u0 = _shard_index() * Ul
        D, Wd = table.shape

        def one(carry, xs):
            lt, ss, ck, wm, dr = carry
            u, e, t, v = xs
            lu = u - u0
            in_shard = (lu >= 0) & (lu < Ul)
            vv = v & in_shard
            local = session.SessionState(lt, ss, ck, wm, jnp.int32(0))
            st, c_in, c_carry = session.step(
                local, jnp.where(vv, lu, -1), e, t, vv,
                gap_ms=gap_ms, lateness_ms=lateness_ms)
            # global watermark / drop facts from the replicated batch
            # (identical math to _session_fold)
            batch_max = jnp.max(jnp.where(v, t, NEG))
            new_wm = jnp.maximum(wm, batch_max)
            min_t = wm - lateness_ms
            ok = (v & (t >= min_t) & (u >= 0) & (u < user_capacity))
            new_dr = dr + jnp.sum(v.astype(jnp.int32)) \
                - jnp.sum(ok.astype(jnp.int32))
            det_bin = jnp.clip(
                jnp.maximum(now_rel - jnp.max(jnp.where(v, t, NEG)), 0)
                // LAT_BIN_MS, 0, LAT_BINS - 1)
            ys = []
            for closed in (_globalize(c_in, u0),
                           _globalize(c_carry, u0)):
                zero = cms.CMSState(
                    table=jnp.zeros((D, Wd), jnp.int32),
                    total=jnp.int32(0))
                loc = cms.update(zero, closed.user, closed.clicks,
                                 closed.valid)
                ys.append((loc.table, loc.total, closed.user,
                           closed.valid,
                           jnp.sum(closed.valid.astype(jnp.int32)),
                           jnp.sum(jnp.where(closed.valid,
                                             closed.clicks, 0))))
            stack = tuple(jnp.stack(parts) for parts in zip(*ys))
            return (st.last_time, st.sess_start, st.clicks, new_wm,
                    new_dr), stack + (det_bin,)

        (lt, ss, ck, wm, dr), ys = jax.lax.scan(
            one, (lt, ss, ck, wm, dr),
            (user_idx, event_type, event_time, valid))
        d_tab, d_tot, c_user, c_valid, c_n, c_clicks, det_bins = ys

        # the deferred merges: ONE stacked psum for the CMS deltas, ONE
        # for the packed scalar counters, ONE all_gather per closed-row
        # column — vs one of each per (batch, closure) in the loop arm
        d_tab = jax.lax.psum(d_tab, MESH_AXES)              # [K, 2, D, Wd]
        scalars = jax.lax.psum(
            jnp.stack([d_tot, c_n, c_clicks], axis=-1),
            MESH_AXES)                                      # [K, 2, 3]
        g = functools.partial(jax.lax.all_gather,
                              axis_name=MESH_AXES, axis=2, tiled=True)
        c_user = g(c_user)                                  # [K, 2, B*n]
        c_valid = g(c_valid)

        # collective-free replay: the candidate ring consumes every
        # closure against the SAME evolving CMS prefix the per-batch
        # arm used (delta adds are reassociated, values identical)
        K2 = d_tab.shape[0] * 2
        def absorb(carry, xs):
            table, total, tkk, tke, cn, cl, hist = carry
            dt, sc, gu, gv, db = xs
            table = table + dt
            total = total + sc[0]
            tk = cms.update_topk(cms.CMSState(table, total),
                                 cms.TopKState(tkk, tke), gu, gv)
            return (table, total, tk.keys, tk.ests, cn + sc[1],
                    cl + sc[2], hist.at[db].add(sc[1])), None

        (table, total, tkk, tke, cn, cl, hist), _ = jax.lax.scan(
            absorb, (table, total, tkk, tke, cn, cl, hist),
            (d_tab.reshape((K2,) + d_tab.shape[2:]),
             scalars.reshape(K2, 3),
             c_user.reshape(K2, -1),
             c_valid.reshape(K2, -1),
             jnp.repeat(det_bins, 2)))
        return (lt, ss, ck, wm, dr, table, total, tkk, tke, cn, cl,
                hist)

    mapped = shard_map(
        body_hoisted if hoist else body_per_batch, mesh=mesh,
        in_specs=_SESS_STATE_SPECS + (P(), P(None, None), P(None, None),
                                      P(None, None), P(None, None)),
        out_specs=_SESS_STATE_SPECS,
    )
    return jax.jit(mapped)


def _session_flush_fold(last_time, sess_start, clicks, watermark, dropped,
                        cms_table, cms_total, tk_keys, tk_ests, closed_n,
                        clicks_n, lat_hist, now_rel, *, gap_ms: int,
                        lateness_ms: int, force: bool):
    Ul = last_time.shape[0]
    u0 = _shard_index() * Ul
    local = session.SessionState(last_time, sess_start, clicks,
                                 watermark, dropped)
    st, expired = session.flush(local, gap_ms=gap_ms,
                                lateness_ms=lateness_ms, force=force)
    cms_state = cms.CMSState(cms_table, cms_total)
    topk = cms.TopKState(tk_keys, tk_ests)
    closed = _globalize(expired, u0)
    dt, dn = _cms_delta_psum(cms_table.shape, closed)
    cms_state = cms.CMSState(cms_state.table + dt, cms_state.total + dn)
    gathered = _gather_closed(closed)
    topk = cms.update_topk(cms_state, topk, gathered.user, gathered.valid)
    closed_n = closed_n + jax.lax.psum(
        jnp.sum(closed.valid.astype(jnp.int32)), MESH_AXES)
    clicks_n = clicks_n + jax.lax.psum(
        jnp.sum(jnp.where(closed.valid, closed.clicks, 0)), MESH_AXES)
    if not force:
        # time-expired closures: per-row due latency, shard-local rows
        # psum into the replicated histogram (forced closures at close()
        # are cut early and carry no meaningful latency)
        due = expired.end + (gap_ms + lateness_ms)
        delta = _hist_rows(jnp.zeros((LAT_BINS,), jnp.int32),
                           jnp.maximum(now_rel - due, 0), expired.valid)
        lat_hist = lat_hist + jax.lax.psum(delta, MESH_AXES)
    return (st.last_time, st.sess_start, st.clicks, st.watermark,
            st.dropped, cms_state.table, cms_state.total, topk.keys,
            topk.ests, closed_n, clicks_n, lat_hist)


@functools.lru_cache(maxsize=None)
def _build_session_flush(mesh: Mesh, gap_ms: int, lateness_ms: int,
                         force: bool):
    def body(*args):
        return _session_flush_fold(*args, gap_ms=gap_ms,
                                   lateness_ms=lateness_ms, force=force)

    mapped = shard_map(body, mesh=mesh,
                       in_specs=_SESS_STATE_SPECS + (P(),),
                       out_specs=_SESS_STATE_SPECS)
    return jax.jit(mapped)


# ----------------------------------------------------------------------
# SALSA-mode session kernels (ISSUE 13): the merge-on-overflow plane is
# NOT psum-linear (merge bits + byte re-encode), so the fixed path's
# per-shard-delta + psum allreduce does not apply.  It does not need
# to: the closed-session ROWS are already all_gathered for the
# replicated candidate ring, and the SALSA transition is a multiset
# homomorphism (ops/salsa.py), so every shard folds the SAME gathered
# closure rows into its replicated plane and lands on a bit-identical
# state — a psum-FREE merge, 3 gathers per closure group and zero
# extra collectives.  Scalar counters/histogram fall out of the same
# gathered rows (replicated sums), dropping the fixed path's counter
# psums too.
# ----------------------------------------------------------------------

_SESS_SALSA_STATE_SPECS = (P(MESH_AXES), P(MESH_AXES), P(MESH_AXES),
                           P(), P(),
                           P(), P(), P(), P(),      # salsa table/m1/m2/total
                           P(), P(), P(), P(), P())  # ring + counters + hist


def _gather_closed3(closed: session.ClosedSessions):
    """all_gather just the columns the SALSA absorb needs (user,
    clicks, valid) — 3 collectives vs _gather_closed's 5."""
    g = functools.partial(jax.lax.all_gather, axis_name=MESH_AXES,
                          tiled=True)
    return g(closed.user), g(closed.clicks), g(closed.valid)


def _session_fold_salsa(last_time, sess_start, clicks, watermark, dropped,
                        s_table, s_m1, s_m2, s_total, tk_keys, tk_ests,
                        closed_n, clicks_n, lat_hist, now_rel,
                        user_idx, event_type, event_time, valid,
                        *, gap_ms: int, lateness_ms: int,
                        user_capacity: int):
    """One batch folded into a user shard + the replicated SALSA plane.

    Mirrors ``_session_fold``'s absorb order (in-batch closures, then
    carried) so the plane equals the single-device engine's bit for
    bit — the homomorphism means batch boundaries and row order inside
    the gathered closure sets cannot matter."""
    Ul = last_time.shape[0]
    u0 = _shard_index() * Ul
    lu = user_idx - u0
    in_shard = (lu >= 0) & (lu < Ul)
    v = valid & in_shard

    local = session.SessionState(last_time, sess_start, clicks,
                                 watermark, jnp.int32(0))
    st, closed_in, closed_carry = session.step(
        local, jnp.where(v, lu, -1), event_type, event_time, v,
        gap_ms=gap_ms, lateness_ms=lateness_ms)

    batch_max = jnp.max(jnp.where(valid, event_time, NEG))
    new_wm = jnp.maximum(watermark, batch_max)
    min_t = watermark - lateness_ms
    ok = (valid & (event_time >= min_t) & (user_idx >= 0)
          & (user_idx < user_capacity))
    new_dropped = dropped + jnp.sum(valid.astype(jnp.int32)) \
        - jnp.sum(ok.astype(jnp.int32))

    cms_state = salsa.SalsaState(s_table, s_m1, s_m2, s_total)
    topk = cms.TopKState(tk_keys, tk_ests)
    det_lat = jnp.maximum(
        now_rel - jnp.max(jnp.where(valid, event_time, NEG)), 0)
    det_bin = jnp.clip(det_lat // LAT_BIN_MS, 0, LAT_BINS - 1)
    for closed in (_globalize(closed_in, u0), _globalize(closed_carry, u0)):
        gu, gc, gv = _gather_closed3(closed)
        cms_state = salsa.update(cms_state, gu, gc, gv)
        topk = cms.update_topk(cms_state, topk, gu, gv)
        n_closed = jnp.sum(gv.astype(jnp.int32))
        closed_n = closed_n + n_closed
        lat_hist = lat_hist.at[det_bin].add(n_closed)
        clicks_n = clicks_n + jnp.sum(jnp.where(gv, gc, 0))

    return (st.last_time, st.sess_start, st.clicks, new_wm, new_dropped,
            cms_state.table, cms_state.m1, cms_state.m2, cms_state.total,
            topk.keys, topk.ests, closed_n, clicks_n, lat_hist)


@functools.lru_cache(maxsize=None)
def _build_session_step_salsa(mesh: Mesh, gap_ms: int, lateness_ms: int,
                              user_capacity: int):
    def body(*args):
        return _session_fold_salsa(*args, gap_ms=gap_ms,
                                   lateness_ms=lateness_ms,
                                   user_capacity=user_capacity)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=_SESS_SALSA_STATE_SPECS + (P(), P(), P(), P(), P()),
        out_specs=_SESS_SALSA_STATE_SPECS,
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _build_session_scan_salsa(mesh: Mesh, gap_ms: int, lateness_ms: int,
                              user_capacity: int):
    """Hoisted scanned SALSA session fold: the scan body is
    collective-FREE (per-batch per-closure shard-local closed rows ride
    the scan ys), then ONE all_gather per closed-row column merges them
    post-scan and a collective-free replay folds the 2K closure groups
    into the replicated plane + ring against the same evolving prefix
    states the per-batch arm saw — 3 collectives per dispatch,
    bit-identical output (the PR 12 session treatment, minus the CMS
    delta psum that SALSA does not need)."""

    def body(lt, ss, ck, wm, dr, s_table, s_m1, s_m2, s_total, tkk, tke,
             cn, cl, hist, now_rel, user_idx, event_type, event_time,
             valid):
        Ul = lt.shape[0]
        u0 = _shard_index() * Ul

        def one(carry, xs):
            lt, ss, ck, wm, dr = carry
            u, e, t, v = xs
            lu = u - u0
            in_shard = (lu >= 0) & (lu < Ul)
            vv = v & in_shard
            local = session.SessionState(lt, ss, ck, wm, jnp.int32(0))
            st, c_in, c_carry = session.step(
                local, jnp.where(vv, lu, -1), e, t, vv,
                gap_ms=gap_ms, lateness_ms=lateness_ms)
            batch_max = jnp.max(jnp.where(v, t, NEG))
            new_wm = jnp.maximum(wm, batch_max)
            min_t = wm - lateness_ms
            ok = (v & (t >= min_t) & (u >= 0) & (u < user_capacity))
            new_dr = dr + jnp.sum(v.astype(jnp.int32)) \
                - jnp.sum(ok.astype(jnp.int32))
            det_bin = jnp.clip(
                jnp.maximum(now_rel - jnp.max(jnp.where(v, t, NEG)), 0)
                // LAT_BIN_MS, 0, LAT_BINS - 1)
            ys = []
            for closed in (_globalize(c_in, u0),
                           _globalize(c_carry, u0)):
                ys.append((closed.user, closed.clicks, closed.valid))
            stack = tuple(jnp.stack(parts) for parts in zip(*ys))
            return (st.last_time, st.sess_start, st.clicks, new_wm,
                    new_dr), stack + (det_bin,)

        (lt, ss, ck, wm, dr), ys = jax.lax.scan(
            one, (lt, ss, ck, wm, dr),
            (user_idx, event_type, event_time, valid))
        c_user, c_clicks, c_valid, det_bins = ys

        # the deferred merge: ONE all_gather per closed-row column —
        # no CMS-delta psum (homomorphic replicated fold), no counter
        # psum (counters recompute from the gathered rows)
        g = functools.partial(jax.lax.all_gather,
                              axis_name=MESH_AXES, axis=2, tiled=True)
        c_user = g(c_user)                           # [K, 2, B*n]
        c_clicks = g(c_clicks)
        c_valid = g(c_valid)

        K2 = c_user.shape[0] * 2

        def absorb(carry, xs):
            table, m1, m2, total, tkk, tke, cn, cl, hist = carry
            gu, gc, gv, db = xs
            cm = salsa.update(salsa.SalsaState(table, m1, m2, total),
                              gu, gc, gv)
            tk = cms.update_topk(cm, cms.TopKState(tkk, tke), gu, gv)
            nc = jnp.sum(gv.astype(jnp.int32))
            return (cm.table, cm.m1, cm.m2, cm.total, tk.keys, tk.ests,
                    cn + nc, cl + jnp.sum(jnp.where(gv, gc, 0)),
                    hist.at[db].add(nc)), None

        (s_table, s_m1, s_m2, s_total, tkk, tke, cn, cl, hist), _ = \
            jax.lax.scan(
                absorb,
                (s_table, s_m1, s_m2, s_total, tkk, tke, cn, cl, hist),
                (c_user.reshape(K2, -1),
                 c_clicks.reshape(K2, -1),
                 c_valid.reshape(K2, -1),
                 jnp.repeat(det_bins, 2)))
        return (lt, ss, ck, wm, dr, s_table, s_m1, s_m2, s_total, tkk,
                tke, cn, cl, hist)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=_SESS_SALSA_STATE_SPECS + (P(), P(None, None),
                                            P(None, None), P(None, None),
                                            P(None, None)),
        out_specs=_SESS_SALSA_STATE_SPECS,
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _build_session_flush_salsa(mesh: Mesh, gap_ms: int, lateness_ms: int,
                               force: bool):
    def body(last_time, sess_start, clicks, watermark, dropped,
             s_table, s_m1, s_m2, s_total, tk_keys, tk_ests, closed_n,
             clicks_n, lat_hist, now_rel):
        Ul = last_time.shape[0]
        u0 = _shard_index() * Ul
        local = session.SessionState(last_time, sess_start, clicks,
                                     watermark, dropped)
        st, expired = session.flush(local, gap_ms=gap_ms,
                                    lateness_ms=lateness_ms, force=force)
        closed = _globalize(expired, u0)
        gu, gc, gv = _gather_closed3(closed)
        cms_state = salsa.update(
            salsa.SalsaState(s_table, s_m1, s_m2, s_total), gu, gc, gv)
        topk = cms.update_topk(cms_state, cms.TopKState(tk_keys, tk_ests),
                               gu, gv)
        closed_n = closed_n + jnp.sum(gv.astype(jnp.int32))
        clicks_n = clicks_n + jnp.sum(jnp.where(gv, gc, 0))
        if not force:
            # per-row due latency needs the expired rows' END times —
            # gather them only on this (flush-cadence) path
            gend = jax.lax.all_gather(expired.end, axis_name=MESH_AXES,
                                      tiled=True)
            due = gend + (gap_ms + lateness_ms)
            lat_hist = _hist_rows(lat_hist,
                                  jnp.maximum(now_rel - due, 0), gv)
        return (st.last_time, st.sess_start, st.clicks, st.watermark,
                st.dropped, cms_state.table, cms_state.m1, cms_state.m2,
                cms_state.total, topk.keys, topk.ests, closed_n,
                clicks_n, lat_hist)

    mapped = shard_map(body, mesh=mesh,
                       in_specs=_SESS_SALSA_STATE_SPECS + (P(),),
                       out_specs=_SESS_SALSA_STATE_SPECS)
    return jax.jit(mapped)


class ShardedSessionCMSEngine(SessionCMSEngine):
    """Session + CMS engine with per-user state sharded over the whole
    mesh (user axis = flattened ``data x campaign``).

    Sessionization is per-key-sequential, so its state shards by USER —
    the reference's analog is the keyed shuffle into per-partition
    processors with a different key field
    (``AdvertisingTopologyNative.java:118-119``).  Each shard sessionizes
    the replicated batch masked to its own users; closed sessions merge
    into the replicated CMS via per-shard delta + ``psum`` (the
    sketch-merge allreduce) and into the replicated candidate ring via
    ``all_gather``.  Bit-identical to the single-device engine (tested).
    """

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 mesh: Mesh, campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 gap_ms: int = 30_000, user_capacity: int = 1 << 16,
                 cms_depth: int = 4, cms_width: int = 2048,
                 top_k: int = 16, candidate_capacity: int | None = None,
                 input_format: str = "json"):
        n_shards = mesh.devices.size
        if user_capacity % n_shards:
            # Raise rather than silently pad: a padded capacity would
            # accept user ids the single-device engine drops (breaking
            # bit-identity) and change the checkpoint geometry.
            raise ValueError(
                f"user_capacity {user_capacity} not divisible by mesh "
                f"size {n_shards}")
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, gap_ms=gap_ms,
                         user_capacity=user_capacity, cms_depth=cms_depth,
                         cms_width=cms_width, top_k=top_k,
                         candidate_capacity=candidate_capacity,
                         input_format=input_format)
        if self.cms_stages == 2:
            # The SF small stage refreshes from fat-stage estimates at
            # update time; shard maxima over it undercut summed true
            # counts (cms.merge2) — there is no sound distributed merge.
            raise ValueError(
                "the sharded session engine does not support "
                "jax.cms.stages=2 (small-stage maxima do not merge "
                "soundly); use stages=1 with mode=fixed or salsa")
        self.mesh = mesh
        self._place()

    def _place(self) -> None:
        """(Re-)apply mesh shardings to session/CMS/ring state."""
        mesh = self.mesh
        user = NamedSharding(mesh, P(MESH_AXES))
        rep = NamedSharding(mesh, P())
        self.state = session.SessionState(
            last_time=jax.device_put(self.state.last_time, user),
            sess_start=jax.device_put(self.state.sess_start, user),
            clicks=jax.device_put(self.state.clicks, user),
            watermark=jax.device_put(self.state.watermark, rep),
            dropped=jax.device_put(self.state.dropped, rep))
        if self.cms_mode == "salsa":
            self.cms = salsa.SalsaState(
                table=jax.device_put(self.cms.table, rep),
                m1=jax.device_put(self.cms.m1, rep),
                m2=jax.device_put(self.cms.m2, rep),
                total=jax.device_put(self.cms.total, rep))
        else:
            self.cms = cms.CMSState(
                table=jax.device_put(self.cms.table, rep),
                total=jax.device_put(self.cms.total, rep))
        self.topk = cms.TopKState(
            keys=jax.device_put(self.topk.keys, rep),
            ests=jax.device_put(self.topk.ests, rep))
        self._closed_dev = jax.device_put(self._closed_dev, rep)
        self._clicks_dev = jax.device_put(self._clicks_dev, rep)
        self.lat_hist = jax.device_put(self.lat_hist, rep)

    def _carry(self):
        cms_parts = (tuple(self.cms) if self.cms_mode == "salsa"
                     else (self.cms.table, self.cms.total))
        return ((self.state.last_time, self.state.sess_start,
                 self.state.clicks, self.state.watermark,
                 self.state.dropped) + cms_parts
                + (self.topk.keys, self.topk.ests, self._closed_dev,
                   self._clicks_dev, self.lat_hist))

    def _uncarry(self, out) -> None:
        if self.cms_mode == "salsa":
            (lt, ss, ck, wm, dr, table, m1, m2, total, tkk, tke,
             self._closed_dev, self._clicks_dev, self.lat_hist) = out
            self.cms = salsa.SalsaState(table, m1, m2, total)
        else:
            (lt, ss, ck, wm, dr, table, total, tkk, tke,
             self._closed_dev, self._clicks_dev, self.lat_hist) = out
            self.cms = cms.CMSState(table, total)
        self.state = session.SessionState(lt, ss, ck, wm, dr)
        self.topk = cms.TopKState(tkk, tke)

    def _device_step(self, batch) -> None:
        build = (_build_session_step_salsa if self.cms_mode == "salsa"
                 else _build_session_step)
        fn = build(self.mesh, self.gap_ms, self.lateness,
                   self.user_capacity)
        self._uncarry(fn(*self._carry(), self._now_rel(),
                         jnp.asarray(batch.user_idx),
                         jnp.asarray(batch.event_type),
                         jnp.asarray(batch.event_time),
                         jnp.asarray(batch.valid)))

    def _device_scan(self, user_idx, event_type, event_time, valid) -> None:
        if self.cms_mode == "salsa":
            fn = _build_session_scan_salsa(self.mesh, self.gap_ms,
                                           self.lateness,
                                           self.user_capacity)
        else:
            fn = _build_session_scan(self.mesh, self.gap_ms,
                                     self.lateness, self.user_capacity,
                                     True)
        self._uncarry(fn(*self._carry(), self._now_rel(), user_idx,
                         event_type, event_time, valid))

    def attach_obs(self, registry, lifecycle: bool = False,
                   spans=None, occupancy=None, xfer=None,
                   shard=None) -> None:
        super().attach_obs(registry, lifecycle, spans=spans,
                           occupancy=occupancy, xfer=xfer, shard=shard)
        self._obs_reg = registry

    def collective_report(self, k: int | None = None) -> dict:
        """Per-dispatch collective costs of the compiled session kernels
        — the ISSUE 12 HLO-measured number for the hoisted session scan
        (collective-free scan body, stacked post-scan merges)."""
        from streambench_tpu.parallel import collectives

        k = int(k or self.scan_batches)
        B = self.batch_size
        zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
        carry = self._carry()
        now = jnp.int32(0)
        if self.cms_mode == "salsa":
            step_fn = _build_session_step_salsa(
                self.mesh, self.gap_ms, self.lateness, self.user_capacity)
            scan_fn = _build_session_scan_salsa(
                self.mesh, self.gap_ms, self.lateness, self.user_capacity)
        else:
            step_fn = _build_session_step(
                self.mesh, self.gap_ms, self.lateness, self.user_capacity)
            scan_fn = _build_session_scan(
                self.mesh, self.gap_ms, self.lateness, self.user_capacity,
                True)
        report = {
            "batch_events": B,
            "scan_batches": k,
            "step": collectives.report_for(
                step_fn, *carry, now, zi(B), zi(B), zi(B),
                jnp.zeros((B,), bool)),
            "scan": collectives.report_for(
                scan_fn, *carry, now, zi(k, B), zi(k, B), zi(k, B),
                jnp.zeros((k, B), bool), scan_len=k),
        }
        reg = getattr(self, "_obs_reg", None)
        if reg is not None:
            collectives.publish_gauges(reg, report)
        return report

    def _sharded_flush(self, force: bool) -> None:
        build = (_build_session_flush_salsa if self.cms_mode == "salsa"
                 else _build_session_flush)
        fn = build(self.mesh, self.gap_ms, self.lateness, force)
        self._uncarry(fn(*self._carry(), self._now_rel()))

    def _drain_device(self) -> None:
        self._sharded_flush(force=False)
        self._span_start = None

    def close(self) -> None:
        self._sharded_flush(force=True)
        self._write_heavy_hitters()

    def restore(self, snap) -> None:
        super().restore(snap)
        self._place()
