"""Multi-host distributed backend: jax.distributed over DCN + ICI.

The reference has no in-repo comm backend — engines bring their own
(Storm Netty, Flink Akka, Spark RPC, Apex buffer-server; SURVEY.md §2
census) and cross-system transport is Kafka TCP + Redis RESP.  The
TPU-native equivalent (§5.8): XLA collectives over ICI within a host's
chips and over DCN between hosts, coordinated by the jax distributed
runtime.  This module is that backend's thin control plane:

- ``init_distributed`` — bring the process into the global runtime
  (coordinator + N processes; the NCCL/MPI-rank analog);
- ``global_mesh`` — one mesh over ALL hosts' devices, so the same
  ``shard_map`` engine code scales from 1 chip to a pod: batch axis spans
  hosts (each host feeds its local events), campaign axis shards state;
- ``cross_host_barrier`` — the DCN barrier that replaces the fork's
  Redis spin-wait (``AdvertisingTopologyNative.java:228-254``) inside the
  engine (the Redis protocol stays available for harness compatibility,
  ``engine.microbatch.RedisWindowBarrier``);
- ``DistributedWindowEngine`` — the sharded engine with (a) per-host
  batch ingestion into a global array (each host contributes its local
  shard; no host ever materializes the global batch) and (b) shard-local
  Redis flushes: every host writes exactly the campaign shards it owns,
  so the writeback parallelizes with no duplicate rows.

Tested for real in CI: two OS processes, four virtual CPU devices each,
gloo collectives between them (``tests/test_distributed.py``) — the same
embedded-cluster trick the reference uses for multi-node coverage
(``ApplicationWithDCWithoutDeserializerTest.java:19-45``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.io.redis_schema import RedisLike
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.parallel.mesh import CAMPAIGN_AXIS, DATA_AXIS
from streambench_tpu.parallel.sharded import ShardedWindowEngine


@dataclass(frozen=True)
class DistContext:
    process_id: int
    num_processes: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> DistContext:
    """Join the jax distributed runtime; no-op for single-process runs.

    Arguments default to the ``STREAMBENCH_COORDINATOR`` /
    ``STREAMBENCH_NUM_PROCESSES`` / ``STREAMBENCH_PROCESS_ID`` env vars
    (on real TPU pods jax can also auto-detect all three from the cluster
    environment, in which case calling ``jax.distributed.initialize()``
    with no args is equivalent).
    """
    import jax

    coordinator_address = (coordinator_address
                           or os.environ.get("STREAMBENCH_COORDINATOR"))
    if num_processes is None:
        num_processes = int(os.environ.get("STREAMBENCH_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("STREAMBENCH_PROCESS_ID", "0"))
    if num_processes <= 1:
        return DistContext(0, 1)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return DistContext(process_id, num_processes)


def global_mesh(campaign: int = 1):
    """(data x campaign) mesh over every device of every host.

    ``build_mesh`` already defaults to ``jax.devices()``, which under the
    distributed runtime is the GLOBAL device list — this alias exists to
    make that contract explicit at multi-host call sites."""
    from streambench_tpu.parallel.mesh import build_mesh

    return build_mesh(campaign=campaign)


def cross_host_barrier(name: str) -> None:
    """All hosts rendezvous (DCN); the Redis-spin replacement."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


class DistributedWindowEngine(ShardedWindowEngine):
    """Sharded engine across hosts: local ingest, shard-owned flushes.

    Each process tails its own partition(s) of the topic and encodes a
    LOCAL batch of ``jax_batch_size`` rows; ``make_array_from_process_
    local_data`` assembles the global batch (size ``B x num_processes``)
    without any host ever holding it.  ``base_time_ms`` must be agreed
    across hosts up front (window ids are relative to it): pass the
    dataset start, or any value all processes compute identically.
    """

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 mesh, base_time_ms: int,
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, mesh, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.encoder.set_base_time(base_time_ms)

    # -- the ONE copy of the lockstep ring-safety invariant ------------
    # Every device-program call in this engine is an SPMD collective, so
    # drain decisions must be byte-identical on every process: they are
    # always computed from GLOBAL (voted/allgathered) spans through these
    # two helpers — never from local batch times (the base class also
    # halves over-wide batches, a shape change that would diverge).

    def drain_due(self, lo: int, hi: int) -> bool:
        """Deterministic drain decision for one lockstep step with
        global event-time span [lo, hi].  Raises if a single step
        outspans the ring (lockstep batches cannot halve)."""
        if hi - lo > self._span_guard:
            raise ValueError(
                f"one lockstep batch spans {hi - lo} ms of event time; "
                f"ring-safe span is {self._span_guard} ms — lower "
                "jax_batch_size or raise jax_window_slots (lockstep "
                "batches cannot halve: shapes must match across "
                "processes)")
        return (self._span_start is not None
                and hi - self._span_start > self._span_guard)

    def apply_drain(self, lo: int) -> None:
        with self.tracer.span("drain"):
            self._drain_device()
        self._span_start = lo

    def note_span(self, lo: int) -> None:
        if self._span_start is None:
            self._span_start = lo

    def _fold(self, batch) -> None:
        """Lockstep fold of one batch: span accounting on GLOBAL batch
        extrema, exchanged with one tiny host allgather per step (the
        batched-vote catchup path in ``run_distributed_catchup`` amortizes
        this to one exchange per round)."""
        from streambench_tpu.utils.ids import now_ms as _now_ms

        gmin, gmax = self._global_batch_span(batch)
        if gmax >= gmin:  # any process had data
            if self.drain_due(gmin, gmax):
                self.apply_drain(gmin)
            else:
                self.note_span(gmin)
        self._device_step(batch)
        self.events_processed += batch.n
        self.last_event_ms = _now_ms()

    def _global_batch_span(self, batch) -> tuple[int, int]:
        """(min, max) absolute event time over ALL processes' batches."""
        from jax.experimental import multihost_utils

        base = batch.base_time_ms
        if batch.n:
            vt = batch.event_time[:batch.n]
            lo, hi = int(vt.min()) + base, int(vt.max()) + base
        else:
            lo, hi = np.iinfo(np.int64).max, np.iinfo(np.int64).min
        spans = multihost_utils.process_allgather(
            np.array([lo, hi], np.int64))
        return int(spans[:, 0].min()), int(spans[:, 1].max())

    # Lockstep: collective call counts must match across processes, so
    # no multi-batch chunking of any kind.
    SCAN_SUPPORTED = False

    def process_chunk(self, lines: list[bytes]) -> int:
        return self.process_lines(lines)

    def step_empty(self) -> None:
        """Participate in one step with no local data (peers still have
        events; collectives need every process)."""
        self._fold(self._encode([], self.batch_size))

    def _device_step(self, batch) -> None:
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from streambench_tpu.parallel.sharded import sharded_step

        sh = NamedSharding(self.mesh, P(DATA_AXIS))
        cols = [jax.make_array_from_process_local_data(sh, col)
                for col in (batch.ad_idx, batch.event_type,
                            batch.event_time, batch.valid)]
        self.state = sharded_step(
            self.mesh, self.state, self.join_table,
            cols[0], cols[1], cols[2], cols[3],
            divisor_ms=self.divisor, lateness_ms=self.lateness)

    def fold_round(self, batches: list, steps: int) -> None:
        """Fold ``steps`` lockstep batches in ONE device dispatch (the
        scanned sharded step, ``_device_scan``) with NO host exchanges.

        The caller has already agreed the round globally — every process
        calls with the same ``steps`` and drain decisions were taken from
        voted global spans — so the only cross-host traffic here is the
        device collectives inside the scan body.  Local batches short of
        ``steps`` are padded with all-invalid batches (no-ops in the
        kernel; peers still fold real data those iterations).
        """
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from streambench_tpu.utils.ids import now_ms as _now_ms

        if steps <= 0:
            return
        template = batches[0] if batches else self._encode([], self.batch_size)
        sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
        cols = []
        for name in ("ad_idx", "event_type", "event_time", "valid"):
            arrs = [getattr(b, name) for b in batches]
            arrs += [np.zeros_like(getattr(template, name))
                     ] * (steps - len(batches))
            cols.append(jax.make_array_from_process_local_data(
                sh, np.stack(arrs)))
        self._device_scan(*cols)
        self.events_processed += sum(b.n for b in batches)
        self.last_event_ms = _now_ms()

    def process_lines(self, lines: list[bytes]) -> int:
        """One lockstep step per call: at most one batch of lines (the
        driver paces steps; silently chunking like the base class would
        desynchronize collective call counts across processes)."""
        if len(lines) > self.batch_size:
            raise ValueError(
                f"{len(lines)} lines exceed one lockstep batch "
                f"({self.batch_size}); the driver must pace steps")
        with self.tracer.span("encode"):
            batch = self._encode(lines, self.batch_size)
        self._fold(batch)
        return len(lines)

    def _drain_device(self) -> None:
        """Pull ONLY this host's campaign shards of the delta array.

        The counts array is campaign-sharded; each host owns a disjoint
        row range, so hosts flush disjoint campaign sets to Redis — the
        writeback itself is data-parallel across the pod.
        """
        # This engine drains densely per shard; the base class's dirty-row
        # tracker (filled by _fold at large C*W) is unused here and must
        # not accumulate one array per batch forever.
        self._dirty_rows.clear()
        deltas, wids, self.state = wc.flush_deltas(
            self.state, divisor_ms=self.divisor, lateness_ms=self.lateness)
        wids = np.asarray(wids)  # replicated -> addressable everywhere
        base = self.encoder.base_time_ms or 0
        C = self.encoder.num_campaigns
        n_rep = self.mesh.shape[DATA_AXIS]       # replicas per shard
        n_blocks = self.mesh.shape[CAMPAIGN_AXIS]  # distinct shards
        for shard in deltas.addressable_shards:
            # The counts array is replicated over the data axis: several
            # devices (possibly on several hosts) hold each campaign
            # shard.  Elect exactly one GLOBAL owner replica per shard,
            # spread across the replica range so the Redis writeback is
            # load-balanced over hosts instead of all landing on the
            # coordinator (replica ids enumerate host-major).
            rows = shard.data.shape[0]
            row0 = shard.index[0].start or 0
            block = row0 // max(rows, 1)
            owner = (block * n_rep) // n_blocks
            if shard.replica_id != owner:
                continue
            local = np.asarray(shard.data)
            ci, si = np.nonzero(local)
            for c, s in zip(ci.tolist(), si.tolist()):
                wid = int(wids[s])
                gc = row0 + c
                if wid < 0 or gc >= C:  # padding rows
                    continue
                abs_ts = base + wid * self.divisor
                self._pending[(gc, abs_ts)] += int(local[c, s])
        self._span_start = None


def run_distributed_catchup(engine: DistributedWindowEngine, reader,
                            flush_every: int = 64,
                            max_steps: int | None = None,
                            vote_every: int = 8) -> dict:
    """Lockstep catchup over every process's local reader, voting once
    per ``vote_every``-step ROUND instead of once per step.

    Each round: poll + encode up to ``vote_every`` local batches, then
    ONE host allgather exchanges ``[n_batches, span_lo, span_hi]`` per
    process.  That single vote settles (a) the round length (max over
    processes; short processes pad with no-op batches), (b) termination
    (everyone at 0), and (c) the drain decision from the GLOBAL span —
    after which the whole round folds in one scanned device dispatch
    with no further host traffic (replaces the per-step flag vote + the
    per-step span allgather, a 2/step -> 1/round reduction; the fork's
    per-window Redis barrier analog, ``AdvertisingTopologyNative.java:
    228-254``).  The vote cost is measured and returned:
    ``{"events", "steps", "rounds", "votes", "vote_s"}``.
    """
    import time

    from jax.experimental import multihost_utils

    B = engine.batch_size
    NONE_LO, NONE_HI = np.iinfo(np.int64).max, np.iinfo(np.int64).min
    stats = {"events": 0, "steps": 0, "rounds": 0, "votes": 0,
             "vote_s": 0.0}
    from streambench_tpu.engine.runner import StreamRunner

    est_bytes = StreamRunner.EST_EVENT_BYTES
    block_mode = (getattr(engine, "supports_block_ingest", False)
                  and hasattr(reader, "poll_block"))
    carry = b""        # block-mode bytes beyond this round's k batches
    done_local = False
    while max_steps is None or stats["steps"] < max_steps:
        k = vote_every
        if max_steps is not None:
            k = min(k, max_steps - stats["steps"])
        batches = []
        if block_mode and not (done_local and not carry):
            # block-mode ingest (same fast path as the single-host
            # runner; per-process local data, so lockstep alignment is
            # untouched — batches stay local until the vote).  Records
            # can be shorter than the byte estimate, so a read may hold
            # MORE than k batches: the surplus carries to the next round
            # (its bytes are already consumed from the reader).
            data = carry
            budget = B * k * est_bytes - len(carry)
            if not done_local and budget > 0:  # poll_block(0) != "none"
                fresh = reader.poll_block(budget)
                if fresh:
                    data = carry + fresh
                else:
                    done_local = True
            batches, start = engine.encoder.carve_block(
                data, B, max_batches=k)
            carry = data[start:]
        elif not block_mode and not done_local:
            lines = reader.poll(max_records=B * k)
            if not lines:
                done_local = True
            for off in range(0, len(lines), B):
                b = engine._encode(lines[off:off + B], B)
                if b.n:
                    batches.append(b)
        # Vote payload: [has_more, n_batches, lo_0, hi_0, ...] — PER-
        # BATCH spans, so the round driver can reconstruct global
        # per-step spans and place drains mid-round deterministically
        # (a round-level min/max alone would force a hard error whenever
        # a whole round outspans the ring, which sparse journals do).
        # ``has_more`` is separate from the batch count: a poll that
        # returned only unparseable lines yields ZERO batches while the
        # journal still has data behind them — termination must wait for
        # every process to actually run dry, not merely encode nothing
        # this round.
        base = engine.encoder.base_time_ms or 0
        payload = np.empty(2 + 2 * k, np.int64)
        payload[0] = 0 if (done_local and not batches and not carry) else 1
        payload[1] = len(batches)
        payload[2::2], payload[3::2] = NONE_LO, NONE_HI
        for i, b in enumerate(batches):
            vt = b.event_time[:b.n]
            payload[2 + 2 * i] = int(vt.min()) + base
            payload[3 + 2 * i] = int(vt.max()) + base

        t0 = time.perf_counter()
        summary = multihost_utils.process_allgather(payload)
        stats["votes"] += 1
        stats["vote_s"] += time.perf_counter() - t0

        if int(summary[:, 0].max()) == 0:
            break  # every process is dry
        round_steps = int(summary[:, 1].max())
        if round_steps == 0:
            continue  # someone is mid-journal but encoded nothing yet
        step_lo = summary[:, 2::2].min(axis=0)   # [k] global per-step
        step_hi = summary[:, 3::2].max(axis=0)

        # Walk the round's steps, grouping them into drain-separated
        # segments — identical arithmetic on identical voted data, so
        # every process folds the same segments and drains at the same
        # points (engine.drain_due holds the one copy of the invariant).
        seg_start = 0

        def fold_segment(end: int) -> None:
            engine.fold_round(batches[seg_start:end],
                              end - seg_start)

        for i in range(round_steps):
            lo_i, hi_i = int(step_lo[i]), int(step_hi[i])
            if lo_i > hi_i:
                continue  # no process had data at step i
            if engine.drain_due(lo_i, hi_i):
                fold_segment(i)
                seg_start = i
                engine.apply_drain(lo_i)
            else:
                engine.note_span(lo_i)
        fold_segment(round_steps)

        prev = stats["steps"]
        stats["steps"] += round_steps
        stats["rounds"] += 1
        # deterministic flush cadence: same step counts -> same flushes
        if stats["steps"] // flush_every != prev // flush_every:
            engine.flush()
    if carry:
        # max_steps exit with consumed-but-unfolded bytes: rewind the
        # reader so a resume (or checkpoint of reader.offset) replays
        # them instead of silently skipping records
        reader.seek(reader.offset - len(carry))
    engine.flush()
    engine.drain_writes()  # flush() queues on the writer thread; the
    # function's contract is "flushed to Redis", so block until it landed
    stats["events"] = engine.events_processed
    return stats
