"""Multi-host distributed backend: jax.distributed over DCN + ICI.

The reference has no in-repo comm backend — engines bring their own
(Storm Netty, Flink Akka, Spark RPC, Apex buffer-server; SURVEY.md §2
census) and cross-system transport is Kafka TCP + Redis RESP.  The
TPU-native equivalent (§5.8): XLA collectives over ICI within a host's
chips and over DCN between hosts, coordinated by the jax distributed
runtime.  This module is that backend's thin control plane:

- ``init_distributed`` — bring the process into the global runtime
  (coordinator + N processes; the NCCL/MPI-rank analog);
- ``global_mesh`` — one mesh over ALL hosts' devices, so the same
  ``shard_map`` engine code scales from 1 chip to a pod: batch axis spans
  hosts (each host feeds its local events), campaign axis shards state;
- ``cross_host_barrier`` — the DCN barrier that replaces the fork's
  Redis spin-wait (``AdvertisingTopologyNative.java:228-254``) inside the
  engine (the Redis protocol stays available for harness compatibility,
  ``engine.microbatch.RedisWindowBarrier``);
- ``DistributedWindowEngine`` — the sharded engine with (a) per-host
  batch ingestion into a global array (each host contributes its local
  shard; no host ever materializes the global batch) and (b) shard-local
  Redis flushes: every host writes exactly the campaign shards it owns,
  so the writeback parallelizes with no duplicate rows.

Tested for real in CI: two OS processes, four virtual CPU devices each,
gloo collectives between them (``tests/test_distributed.py``) — the same
embedded-cluster trick the reference uses for multi-node coverage
(``ApplicationWithDCWithoutDeserializerTest.java:19-45``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.io.redis_schema import RedisLike
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.parallel.mesh import CAMPAIGN_AXIS, DATA_AXIS
from streambench_tpu.parallel.sharded import ShardedWindowEngine


@dataclass(frozen=True)
class DistContext:
    process_id: int
    num_processes: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> DistContext:
    """Join the jax distributed runtime; no-op for single-process runs.

    Arguments default to the ``STREAMBENCH_COORDINATOR`` /
    ``STREAMBENCH_NUM_PROCESSES`` / ``STREAMBENCH_PROCESS_ID`` env vars
    (on real TPU pods jax can also auto-detect all three from the cluster
    environment, in which case calling ``jax.distributed.initialize()``
    with no args is equivalent).
    """
    import jax

    coordinator_address = (coordinator_address
                           or os.environ.get("STREAMBENCH_COORDINATOR"))
    if num_processes is None:
        num_processes = int(os.environ.get("STREAMBENCH_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("STREAMBENCH_PROCESS_ID", "0"))
    if num_processes <= 1:
        return DistContext(0, 1)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return DistContext(process_id, num_processes)


def global_mesh(campaign: int = 1):
    """(data x campaign) mesh over every device of every host.

    ``build_mesh`` already defaults to ``jax.devices()``, which under the
    distributed runtime is the GLOBAL device list — this alias exists to
    make that contract explicit at multi-host call sites."""
    from streambench_tpu.parallel.mesh import build_mesh

    return build_mesh(campaign=campaign)


def cross_host_barrier(name: str) -> None:
    """All hosts rendezvous (DCN); the Redis-spin replacement."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


class DistributedWindowEngine(ShardedWindowEngine):
    """Sharded engine across hosts: local ingest, shard-owned flushes.

    Each process tails its own partition(s) of the topic and encodes a
    LOCAL batch of ``jax_batch_size`` rows; ``make_array_from_process_
    local_data`` assembles the global batch (size ``B x num_processes``)
    without any host ever holding it.  ``base_time_ms`` must be agreed
    across hosts up front (window ids are relative to it): pass the
    dataset start, or any value all processes compute identically.
    """

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 mesh, base_time_ms: int,
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, mesh, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.encoder.set_base_time(base_time_ms)

    def _fold(self, batch) -> None:
        """Lockstep fold: every device-program call below is an SPMD
        collective, so the drain decision must be byte-identical on every
        process.  The base class decides from LOCAL batch times and can
        halve over-wide batches (shape changes) — both would diverge.
        Here the span accounting runs on GLOBAL batch extrema, exchanged
        with one tiny host allgather per step, and an over-wide global
        batch is a hard error (sized by jax_batch_size x event spacing;
        see class docstring)."""
        from streambench_tpu.utils.ids import now_ms as _now_ms

        gmin, gmax = self._global_batch_span(batch)
        if gmax >= gmin:  # any process had data
            if gmax - gmin > self._span_guard:
                raise ValueError(
                    f"global batch spans {gmax - gmin} ms of event time; "
                    f"ring-safe span is {self._span_guard} ms — lower "
                    "jax_batch_size or raise jax_window_slots (distributed "
                    "mode cannot halve batches: shapes must match across "
                    "processes)")
            if self._span_start is None:
                self._span_start = gmin
            if gmax - self._span_start > self._span_guard:
                with self.tracer.span("drain"):
                    self._drain_device()
                self._span_start = gmin
        self._device_step(batch)
        self.events_processed += batch.n
        self.last_event_ms = _now_ms()

    def _global_batch_span(self, batch) -> tuple[int, int]:
        """(min, max) absolute event time over ALL processes' batches."""
        from jax.experimental import multihost_utils

        base = batch.base_time_ms
        if batch.n:
            vt = batch.event_time[:batch.n]
            lo, hi = int(vt.min()) + base, int(vt.max()) + base
        else:
            lo, hi = np.iinfo(np.int64).max, np.iinfo(np.int64).min
        spans = multihost_utils.process_allgather(
            np.array([lo, hi], np.int64))
        return int(spans[:, 0].min()), int(spans[:, 1].max())

    # Lockstep: collective call counts must match across processes, so
    # no multi-batch chunking of any kind.
    SCAN_SUPPORTED = False

    def process_chunk(self, lines: list[bytes]) -> int:
        return self.process_lines(lines)

    def step_empty(self) -> None:
        """Participate in one step with no local data (peers still have
        events; collectives need every process)."""
        self._fold(self._encode([], self.batch_size))

    def _device_step(self, batch) -> None:
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from streambench_tpu.parallel.sharded import sharded_step

        sh = NamedSharding(self.mesh, P(DATA_AXIS))
        cols = [jax.make_array_from_process_local_data(sh, col)
                for col in (batch.ad_idx, batch.event_type,
                            batch.event_time, batch.valid)]
        self.state = sharded_step(
            self.mesh, self.state, self.join_table,
            cols[0], cols[1], cols[2], cols[3],
            divisor_ms=self.divisor, lateness_ms=self.lateness)

    def process_lines(self, lines: list[bytes]) -> int:
        """One lockstep step per call: at most one batch of lines (the
        driver paces steps; silently chunking like the base class would
        desynchronize collective call counts across processes)."""
        if len(lines) > self.batch_size:
            raise ValueError(
                f"{len(lines)} lines exceed one lockstep batch "
                f"({self.batch_size}); the driver must pace steps")
        with self.tracer.span("encode"):
            batch = self._encode(lines, self.batch_size)
        self._fold(batch)
        return len(lines)

    def _drain_device(self) -> None:
        """Pull ONLY this host's campaign shards of the delta array.

        The counts array is campaign-sharded; each host owns a disjoint
        row range, so hosts flush disjoint campaign sets to Redis — the
        writeback itself is data-parallel across the pod.
        """
        deltas, wids, self.state = wc.flush_deltas(
            self.state, divisor_ms=self.divisor, lateness_ms=self.lateness)
        wids = np.asarray(wids)  # replicated -> addressable everywhere
        base = self.encoder.base_time_ms or 0
        C = self.encoder.num_campaigns
        n_rep = self.mesh.shape[DATA_AXIS]       # replicas per shard
        n_blocks = self.mesh.shape[CAMPAIGN_AXIS]  # distinct shards
        for shard in deltas.addressable_shards:
            # The counts array is replicated over the data axis: several
            # devices (possibly on several hosts) hold each campaign
            # shard.  Elect exactly one GLOBAL owner replica per shard,
            # spread across the replica range so the Redis writeback is
            # load-balanced over hosts instead of all landing on the
            # coordinator (replica ids enumerate host-major).
            rows = shard.data.shape[0]
            row0 = shard.index[0].start or 0
            block = row0 // max(rows, 1)
            owner = (block * n_rep) // n_blocks
            if shard.replica_id != owner:
                continue
            local = np.asarray(shard.data)
            ci, si = np.nonzero(local)
            for c, s in zip(ci.tolist(), si.tolist()):
                wid = int(wids[s])
                gc = row0 + c
                if wid < 0 or gc >= C:  # padding rows
                    continue
                abs_ts = base + wid * self.divisor
                self._pending[(gc, abs_ts)] += int(local[c, s])
        self._span_start = None


def run_distributed_catchup(engine: DistributedWindowEngine, reader,
                            flush_every: int = 64,
                            max_steps: int | None = None) -> int:
    """Lockstep catchup over every process's local reader.

    Each iteration: poll ONE local batch, vote (host allgather) on
    whether any process still has data, fold — processes that ran dry
    feed empty steps so collectives stay aligned — and flush to Redis on
    a deterministic step cadence.  Returns local events processed.
    """
    from jax.experimental import multihost_utils

    steps = 0
    done_local = False
    while max_steps is None or steps < max_steps:
        lines = [] if done_local else reader.poll(
            max_records=engine.batch_size)
        if not lines:
            done_local = True
        flags = multihost_utils.process_allgather(
            np.array([0 if lines else 1], np.int32))
        if int(flags.sum()) == flags.shape[0]:
            break  # every process is dry
        if lines:
            engine.process_lines(lines)
        else:
            engine.step_empty()
        steps += 1
        if steps % flush_every == 0:
            engine.flush()
    engine.flush()
    engine.drain_writes()  # flush() queues on the writer thread; the
    # function's contract is "flushed to Redis", so block until it landed
    return engine.events_processed
