"""Collective-cost accounting from compiled HLO.

The sharded engines' comms claims ("packed wire halves the per-step
gather traffic", "the hoisted scan issues one gather per column per
dispatch instead of K") lived in comments until ISSUE 7; this module
turns a compiled step into the numbers.  It parses the optimized HLO
text of a ``jax.stages.Compiled`` — the program XLA will actually run —
and reports every cross-device collective with its payload size, split
into top-level ops (execute once per dispatch) and loop-body ops
(execute once per ``lax.scan`` iteration).

Accounting model, stated precisely because artifacts cite it:

- **payload bytes** = byte size of the op's output shape (tuple shapes
  sum their leaves).  This is the data a collective makes every
  participant agree on — NOT a link-level model (a ring all-reduce
  moves ~2·(g-1)/g × payload per device); ``group_size`` is recorded
  per op so a reader can apply whichever wire model their fabric uses.
- **per_dispatch** = top-level + ``scan_len`` × loop-body.  The trip
  count of a ``lax.scan`` is a compile-time constant the CALLER knows
  (the [K, B] stack it passed); parsing it back out of the while
  condition would be fragile, so it is an argument.  Collectives inside
  nested loops (none today — CPU scatter loops carry no collectives)
  are counted once per outer iteration; a new kernel that puts a
  collective inside a double loop must extend this.

Pure text processing — importing this module never initializes jax.
"""

from __future__ import annotations

import re
from typing import NamedTuple

# HLO opcode names of cross-device collectives.  ``-start`` covers the
# async forms (the matching ``-done`` carries no new transfer).
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")
# A defining instruction line: ``  %name = <shape> <opcode>(...``.
# The shape may be a tuple ``(s32[8]{0}, s32[8]{0})``; the opcode is the
# first token after it.  Matching the opcode right after `` = `` shapes
# out USE sites (``fusion(... %all-reduce.23)`` mentions the name but
# not ``= ... all-reduce(``).
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\([^)]*\)|\S+?)\s+([\w\-]+)\(")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*->.*\{\s*$")
_BODY_RE = re.compile(r"body=%?([\w.\-]+)")
_GROUPS_RE = re.compile(r"replica_groups=\{\{([\d,]+)\}")


class CollectiveOp(NamedTuple):
    """One collective instruction in the optimized program."""

    kind: str            # base opcode, e.g. "all-reduce"
    name: str            # HLO instruction name
    payload_bytes: int   # output-shape bytes (see module docstring)
    group_size: int      # participants per replica group (0 = unknown)
    computation: str     # enclosing HLO computation
    in_loop: bool        # True when reached through a while body


def shape_bytes(shape: str) -> int:
    """Byte size of an HLO shape string (``s32[3,64]{1,0}`` or a tuple
    ``(s32[64]{0}, f32[64]{0})``).  A scalar ``s32[]`` is one element."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token/opaque shapes carry no payload
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += size * n
    return total


def _loop_computations(text: str) -> set:
    """Names of computations reachable through at least one ``while``
    body.  One fixpoint pass: a while inside a loop body marks its own
    body as a loop computation too (nesting collapses to "in a loop";
    see the module docstring for the counting rule)."""
    # computation -> set of while-body computations its whiles call
    calls: dict[str, set] = {}
    current = ""
    for line in text.splitlines():
        m = _COMP_RE.match(line)
        if m:
            current = m.group(2)
            continue
        for body in _BODY_RE.findall(line):
            calls.setdefault(current, set()).add(body)
    in_loop: set = set()
    frontier = set().union(*calls.values()) if calls else set()
    while frontier:
        in_loop |= frontier
        frontier = set().union(
            *(calls.get(c, set()) for c in frontier)) - in_loop
    return in_loop


def collective_ops(text: str) -> list:
    """Every collective instruction in an optimized-HLO dump, with its
    payload size and whether it sits inside a loop body."""
    loops = _loop_computations(text)
    current = ""
    out = []
    for line in text.splitlines():
        m = _COMP_RE.match(line)
        if m:
            current = m.group(2)
            continue
        d = _DEF_RE.match(line)
        if d is None:
            continue
        name, shape, opcode = d.groups()
        kind = opcode[:-len("-start")] if opcode.endswith("-start") else opcode
        if kind not in COLLECTIVE_KINDS:
            continue
        g = _GROUPS_RE.search(line)
        group = len(g.group(1).split(",")) if g else 0
        out.append(CollectiveOp(
            kind=kind, name=name, payload_bytes=shape_bytes(shape),
            group_size=group, computation=current,
            in_loop=current in loops))
    return out


def summarize(text: str, scan_len: int = 1,
              column_bytes_min: int = 64) -> dict:
    """Aggregate ``collective_ops`` into the per-dispatch view artifacts
    cite.

    ``per_dispatch`` counts top-level ops once and loop-body ops
    ``scan_len`` times.  ``column_bytes`` restricts the byte total to
    ops whose payload is at least ``column_bytes_min`` — the gathered
    batch columns, excluding the scalar drop-counter psums (4 B; the
    default 64 splits them cleanly, a [B] column being >= 64 B for any
    real batch) — because the wire-packing claim is about column
    traffic specifically.
    """
    ops = collective_ops(text)

    def _agg(sel):
        by_kind: dict[str, int] = {}
        total_ops = 0
        total_bytes = 0
        col_ops = 0
        col_bytes = 0
        for op in ops:
            mult = sel(op)
            if not mult:
                continue
            total_ops += mult
            total_bytes += mult * op.payload_bytes
            if op.payload_bytes >= column_bytes_min:
                col_ops += mult
                col_bytes += mult * op.payload_bytes
            by_kind[op.kind] = by_kind.get(op.kind, 0) + mult
        return {"ops": total_ops, "bytes": total_bytes,
                "column_ops": col_ops, "column_bytes": col_bytes,
                "by_kind": by_kind}

    return {
        "scan_len": scan_len,
        "top_level": _agg(lambda op: 0 if op.in_loop else 1),
        "per_loop_iteration": _agg(lambda op: 1 if op.in_loop else 0),
        "per_dispatch": _agg(
            lambda op: scan_len if op.in_loop else 1),
        "ops": [op._asdict() for op in ops],
    }


def publish_gauges(registry, report: dict) -> None:
    """Mirror an engine ``collective_report`` onto obs gauges:
    ``streambench_collective_{ops,bytes}{kernel="step"|"scan"}``."""
    for kernel in ("step", "scan"):
        r = report.get(kernel)
        if not isinstance(r, dict):
            continue
        registry.gauge("streambench_collective_ops",
                       "collective ops per device dispatch",
                       labels={"kernel": kernel}
                       ).set(r["per_dispatch"]["ops"])
        registry.gauge("streambench_collective_bytes",
                       "collective payload bytes per device dispatch",
                       labels={"kernel": kernel}
                       ).set(r["per_dispatch"]["bytes"])


def report_for(fn, *args, scan_len: int = 1,
               column_bytes_min: int = 64) -> dict:
    """``summarize`` of a jitted function's optimized HLO for ``args``.

    ``fn.lower(*args).compile()`` compiles a fresh executable (it does
    not share the jit call cache), so this belongs in bench/obs setup,
    never on a hot path.  The op list is dropped from the result — the
    per-op detail is for tests; artifacts keep the aggregates."""
    text = fn.lower(*args).compile().as_text()
    out = summarize(text, scan_len=scan_len,
                    column_bytes_min=column_bytes_min)
    out.pop("ops")
    return out
