"""Campaign-sharded reach: the MinHash∪HLL planes on the PR 7 mesh,
with query evaluation placed NEXT to the shards (ROADMAP item 3 /
ISSUE 14).

The single-device reach engine (``ops/minhash.py``) materializes a
``[C, k]`` signature plane and a ``[C, R]`` HLL plane per campaign.
Both merges are elementwise (min / max) — commutative, associative,
idempotent — so campaign-sharding is *provably* exact: each campaign's
rows live on exactly one shard, the ingest fold routes every event to
its owner (the ``ShardedHLLEngine`` treatment without the window ring),
and cross-shard state never has to merge at all.

The interesting half is the **query path**.  A ``[Q, C]`` masked batch
query needs, per query, the min over selected campaigns' signatures and
the max over their signatures + registers — campaigns that live on
different shards.  The naive spelling (gather both planes, evaluate
replicated) moves O(C·(k+R)) bytes per dispatch; per-campaign merges
would issue O(C) collectives.  Instead each shard reduces its OWN
campaigns to ``[Q, k]`` / ``[Q, k+R]`` partials and the cross-shard
merge is hoisted to exactly TWO collectives per query dispatch,
independent of C, Q's padding, and the campaign fan-out of the queries:

- ONE ``pmin`` of the ``[Q, k]`` selected-signature minima;
- ONE ``pmax`` of the ``[Q, k + R]`` concatenation of the
  selected-signature maxima and the selected-register maxima (the
  register plane is bitcast-free: register values are tiny non-negative
  ints, so a uint32 view preserves max ordering exactly).

``collective_report()`` parses the compiled HLO and publishes the
measured table (``parallel/collectives.py``) — the bench asserts the
"exactly 2 cross-shard collectives per query dispatch" claim from the
program text, not from this docstring.  Bit-identity with the
single-device engine (planes AND integer collision counts) is the
oracle; ``tests/test_sharded_reach.py`` sweeps it over adversarial
shard splits and seeds.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.engine.sketches import ReachSketchEngine
from streambench_tpu.io.redis_schema import RedisLike
from streambench_tpu.ops import hll, minhash
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.ops.hll import _rank, splitmix32
from streambench_tpu.ops.minhash import EMPTY, salts
from streambench_tpu.ops.windowcount import NEG
from streambench_tpu.parallel.mesh import CAMPAIGN_AXIS, DATA_AXIS
from streambench_tpu.parallel.sharded import data_axis_pad, pad_data_cols
from streambench_tpu.parallel.sketches import _gather_cols, shard_map
from streambench_tpu.utils.ids import now_ms


def pad_campaigns(num_campaigns: int, mesh: Mesh) -> int:
    from streambench_tpu.parallel.sharded import pad_campaigns as _pc

    return _pc(num_campaigns, mesh)


# ----------------------------------------------------------------------
# ingest fold: the minhash.step scatter against shard-local rows
# ----------------------------------------------------------------------

def _reach_fold_local(mins, registers, watermark, join_table,
                      ad, user, et, tm, v, *, view_type: int):
    """Collective-free reach fold over already-replicated columns:
    this shard owns campaigns ``[c0, c0 + Cl)``; everything else
    scatters to the drop slot.  Mirrors ``minhash.step`` exactly (the
    bit-identity oracle) with ``campaign`` rebased shard-locally."""
    Cl, k = mins.shape
    R = registers.shape[1]
    p = R.bit_length() - 1

    campaign = join_table[ad]
    wanted = v & (et == view_type) & (campaign >= 0)
    c0 = jax.lax.axis_index(CAMPAIGN_AXIS) * Cl
    local_c = campaign - c0
    in_shard = wanted & (local_c >= 0) & (local_c < Cl)

    h = splitmix32(user)
    hk = splitmix32(h[:, None] ^ salts(k)[None, :])
    slot = jnp.arange(k, dtype=jnp.int32)[None, :]
    flat = jnp.where(in_shard[:, None], local_c[:, None] * k + slot,
                     Cl * k)
    mins = (mins.reshape(-1)
            .at[flat].min(hk, mode="drop")
            .reshape(Cl, k))

    j = (h & jnp.uint32(R - 1)).astype(jnp.int32)
    rank = _rank(h, p)
    rflat = jnp.where(in_shard, local_c * R + j, Cl * R)
    registers = (registers.reshape(-1)
                 .at[rflat].max(rank.astype(registers.dtype),
                                mode="drop")
                 .reshape(Cl, R))

    # watermark is computed from the replicated columns — a global fact
    # on every device, no collective needed (the _hll_fold_local rule)
    watermark = jnp.maximum(watermark, jnp.max(jnp.where(v, tm, NEG)))
    return mins, registers, watermark


_STATE_SPECS = (P(CAMPAIGN_AXIS, None), P(CAMPAIGN_AXIS, None), P())


@functools.lru_cache(maxsize=None)
def _build_reach_step(mesh: Mesh, view_type: int = 0):
    def body(mins, registers, watermark, join_table,
             ad, user, et, tm, v):
        ad, user, et, tm, v = _gather_cols(ad, user, et, tm, v)
        return _reach_fold_local(mins, registers, watermark, join_table,
                                 ad, user, et, tm, v,
                                 view_type=view_type)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=_STATE_SPECS + (P(),) + (P(DATA_AXIS),) * 5,
        out_specs=_STATE_SPECS)
    return jax.jit(mapped)


@functools.lru_cache(maxsize=None)
def _build_reach_scan(mesh: Mesh, view_type: int = 0,
                      packed: bool = False):
    """Hoisted scan over ``[K, B]`` stacks: the stacked columns gather
    ONCE per dispatch (PR 7/12 style) and the scan body is
    collective-free — reach has no drop counter to psum, so the whole
    dispatch costs exactly the column gathers."""

    def body(mins, registers, watermark, join_table, *cols):
        cols = _gather_cols(*cols)

        def one(carry, xs):
            mn, rg, wm = carry
            if packed:
                pk, u, t = xs
                a, e, v = wc.unpack_columns(pk)
            else:
                a, u, e, t, v = xs
            return _reach_fold_local(mn, rg, wm, join_table,
                                     a, u, e, t, v,
                                     view_type=view_type), None

        carry, _ = jax.lax.scan(one, (mins, registers, watermark), cols)
        return carry

    n_cols = 3 if packed else 5
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=_STATE_SPECS + (P(),) + (P(None, DATA_AXIS),) * n_cols,
        out_specs=_STATE_SPECS)
    return jax.jit(mapped)


# ----------------------------------------------------------------------
# query evaluation next to the shards
# ----------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _build_reach_query(mesh: Mesh):
    """The sharded twin of ``reach.query.batch_query``.

    Each shard reduces ITS campaign rows to per-query partials, then
    the cross-shard merge is exactly TWO collectives per dispatch:

    - ``pmin`` of the ``[Q, k]`` selected-signature minima;
    - ``pmax`` of ONE ``[Q, k + R]`` uint32 concatenation carrying both
      the selected-signature maxima and the selected-register maxima
      (register values are small non-negative ints, so the uint32 view
      preserves max ordering bit-exactly).

    Outputs are replicated and bit-identical to the single-device
    evaluation: min/max merges are order-invariant, and the estimate /
    Jaccard arithmetic runs on the POST-merge replicated arrays — the
    same ``hll.estimate`` graph over the same integers.
    """

    def body(mins, registers, mask, overlap):
        empty = jnp.uint32(EMPTY)
        k = mins.shape[1]
        sel = mask[:, :, None]
        loc_min = jnp.min(jnp.where(sel, mins[None], empty), axis=1)
        loc_sigmax = jnp.max(jnp.where(sel, mins[None], jnp.uint32(0)),
                             axis=1)
        loc_regs = jnp.max(
            jnp.where(sel, registers[None].astype(jnp.uint32), 0),
            axis=1)
        sel_min = jax.lax.pmin(loc_min, CAMPAIGN_AXIS)          # 1 pmin
        packed = jax.lax.pmax(
            jnp.concatenate([loc_sigmax, loc_regs], axis=1),
            CAMPAIGN_AXIS)                                      # 1 pmax
        sel_max = packed[:, :k]
        union_regs = packed[:, k:].astype(registers.dtype)
        agree = jnp.sum(((sel_min == sel_max) & (sel_min != empty))
                        .astype(jnp.int32), axis=1)
        union = hll.estimate(union_regs).astype(jnp.float32)
        jacc = agree.astype(jnp.float32) / jnp.float32(k)
        est = jnp.where(overlap, union * jacc, union)
        return est, union, jacc, agree

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None), P(CAMPAIGN_AXIS, None),
                  P(None, CAMPAIGN_AXIS), P()),
        out_specs=(P(), P(), P(), P()))
    return jax.jit(mapped)


def sharded_reach_init(num_campaigns: int, k: int, num_registers: int,
                       mesh: Mesh) -> minhash.ReachState:
    """Device-placed initial state: planes campaign-sharded, scalars
    replicated.  The campaign axis pads up to a mesh multiple with
    never-touched rows (EMPTY signature / zero registers evaluate to
    reach 0, exactly like an unobserved campaign)."""
    C = pad_campaigns(num_campaigns, mesh)
    rep = NamedSharding(mesh, P())
    return minhash.ReachState(
        mins=jax.device_put(
            jnp.full((C, k), EMPTY, jnp.uint32),
            NamedSharding(mesh, P(CAMPAIGN_AXIS, None))),
        registers=jax.device_put(
            jnp.zeros((C, num_registers), jnp.int32),
            NamedSharding(mesh, P(CAMPAIGN_AXIS, None))),
        watermark=jax.device_put(jnp.int32(NEG), rep),
        dropped=jax.device_put(jnp.int32(0), rep),
    )


class ShardedReachEngine(ReachSketchEngine):
    """Reach engine with both sketch planes sharded on the campaign
    axis of a ``(data, campaign)`` mesh and queries evaluated next to
    the shards (two collectives per query dispatch, measured by
    ``collective_report``).

    Drop-in for :class:`ReachSketchEngine`: same host loop, serving
    attachment (the pushed state refs stay sharded and the attached
    query server evaluates through :meth:`query_callable`), snapshot
    format (planes gather to host arrays), and CLI flags.
    """

    STEP_PACKS = False   # the per-batch step ships separate columns

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 mesh: Mesh, campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 k: int | None = None, registers: int = 256,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, k=k, registers=registers,
                         input_format=input_format)
        self.mesh = mesh
        self._data_pad = data_axis_pad(self.batch_size, mesh)
        self._padded_c = pad_campaigns(self.encoder.num_campaigns, mesh)
        self.state = sharded_reach_init(
            self.encoder.num_campaigns, self.k, self.registers, mesh)
        self.join_table = jax.device_put(
            jnp.asarray(self.encoder.join_table),
            NamedSharding(mesh, P()))

    # -- fold ----------------------------------------------------------
    def _device_step(self, batch) -> None:
        fn = _build_reach_step(self.mesh)
        ad, user, et, tm, va = pad_data_cols(
            self._data_pad, batch.ad_idx, batch.user_idx,
            batch.event_type, batch.event_time, batch.valid)
        mins, regs, wm = fn(self.state.mins, self.state.registers,
                            self.state.watermark, self.join_table,
                            ad, user, et, tm, va)
        self.state = minhash.ReachState(mins, regs, wm,
                                        self.state.dropped)
        self._fold_wall_ms = now_ms()
        if self._dirty_mask is not None:
            # dirty union from the UNPADDED columns (ISSUE 18): pad
            # rows are invalid by construction and must not mark
            self._mark_dirty(batch.ad_idx, batch.valid)

    def _device_scan(self, ad_idx, user_idx, event_type, event_time,
                     valid) -> None:
        fn = _build_reach_scan(self.mesh)
        cols = pad_data_cols(self._data_pad, ad_idx, user_idx,
                             event_type, event_time, valid)
        mins, regs, wm = fn(self.state.mins, self.state.registers,
                            self.state.watermark, self.join_table,
                            *cols)
        self.state = minhash.ReachState(mins, regs, wm,
                                        self.state.dropped)
        self._fold_wall_ms = now_ms()
        if self._dirty_mask is not None:
            self._mark_dirty(ad_idx, valid)

    def _device_scan_packed(self, packed, user_idx, event_time) -> None:
        fn = _build_reach_scan(self.mesh, packed=True)
        cols = pad_data_cols(self._data_pad, packed, user_idx,
                             event_time)
        mins, regs, wm = fn(self.state.mins, self.state.registers,
                            self.state.watermark, self.join_table,
                            *cols)
        self.state = minhash.ReachState(mins, regs, wm,
                                        self.state.dropped)
        self._fold_wall_ms = now_ms()
        if self._dirty_mask is not None:
            self._mark_dirty_packed(packed)

    # -- queries next to the shards ------------------------------------
    def query_callable(self):
        """The evaluator an attached query server dispatches through:
        pads the ``[Q, C]`` mask to the sharded campaign width and runs
        the two-collective program.  Never-touched pad campaigns can't
        be selected (the mask pad is False), so results are bit-
        identical to the single-device ``batch_query``."""
        fn = _build_reach_query(self.mesh)
        pad = self._padded_c - self.encoder.num_campaigns

        def query(mins, registers, mask, overlap):
            mask = np.asarray(mask, bool)
            if pad:
                mask = np.concatenate(
                    [mask, np.zeros((mask.shape[0], pad), bool)],
                    axis=1)
            return fn(mins, registers, jnp.asarray(mask),
                      jnp.asarray(np.asarray(overlap, bool)))

        return query

    def batch_query(self, masks, overlap):
        """Direct sharded evaluation (tests/bench): numpy in/out."""
        est, union, jacc, agree = self.query_callable()(
            self.state.mins, self.state.registers, masks, overlap)
        return (np.asarray(est), np.asarray(union), np.asarray(jacc),
                np.asarray(agree))

    def host_state(self) -> minhash.ReachState:
        """Host-gathered planes TRIMMED to the real campaign count (the
        single-device-comparable view; pad rows are provably inert)."""
        C = self.encoder.num_campaigns
        return minhash.ReachState(
            mins=np.asarray(self.state.mins)[:C],
            registers=np.asarray(self.state.registers)[:C],
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped))

    def estimates(self) -> np.ndarray:
        return np.asarray(minhash.estimate(
            jnp.asarray(self.host_state().registers)))

    # -- obs -----------------------------------------------------------
    def attach_obs(self, registry, lifecycle: bool = False, spans=None,
                   occupancy=None, xfer=None, shard=None) -> None:
        super().attach_obs(registry, lifecycle, spans=spans,
                           occupancy=occupancy, xfer=xfer, shard=shard)
        self._obs_reg = registry

    def collective_report(self, k: int | None = None,
                          query_batch: int = 256) -> dict:
        """Per-dispatch collective costs of the compiled reach kernels,
        parsed from optimized HLO (``parallel/collectives.py``).  The
        ``query`` table is the transferable headline: its per-dispatch
        op count must read exactly 2 (one all-reduce min, one
        all-reduce max) on any multi-shard mesh."""
        from streambench_tpu.parallel import collectives

        k = int(k or self.scan_batches)
        B = self.batch_size + self._data_pad
        st = self.state
        zi = lambda *shape: jnp.zeros(shape, jnp.int32)  # noqa: E731
        scan_fn = _build_reach_scan(self.mesh)
        query_fn = _build_reach_query(self.mesh)
        Q = int(query_batch)
        report = {
            "batch_events": self.batch_size,
            "scan_batches": k,
            "query_batch": Q,
            "step": collectives.report_for(
                _build_reach_step(self.mesh),
                st.mins, st.registers, st.watermark, self.join_table,
                zi(B), zi(B), zi(B), zi(B), jnp.zeros((B,), bool)),
            "scan": collectives.report_for(
                scan_fn, st.mins, st.registers, st.watermark,
                self.join_table, zi(k, B), zi(k, B), zi(k, B), zi(k, B),
                jnp.zeros((k, B), bool), scan_len=k),
            "query": collectives.report_for(
                query_fn, st.mins, st.registers,
                jnp.zeros((Q, self._padded_c), bool),
                jnp.zeros((Q,), bool)),
        }
        reg = getattr(self, "_obs_reg", None)
        if reg is not None:
            collectives.publish_gauges(reg, report)
            q = report["query"]["per_dispatch"]
            reg.gauge("streambench_collective_ops",
                      "collective ops per device dispatch",
                      labels={"kernel": "query"}).set(q["ops"])
            reg.gauge("streambench_collective_bytes",
                      "collective payload bytes per device dispatch",
                      labels={"kernel": "query"}).set(q["bytes"])
        return report

    # -- snapshot / restore (snapshot() inherits: np.asarray gathers
    # the sharded planes to host arrays) --------------------------------
    def restore(self, snap) -> None:
        super().restore(snap)
        # Re-place host-restored planes with mesh shardings, padding the
        # campaign axis (accepts single-device ReachSketchEngine
        # snapshots — the scale-out upgrade path).
        C = self._padded_c
        mins = np.asarray(self.state.mins)
        regs = np.asarray(self.state.registers)
        if mins.shape[0] < C:
            mins = np.concatenate(
                [mins, np.full((C - mins.shape[0], mins.shape[1]),
                               EMPTY, mins.dtype)])
            regs = np.concatenate(
                [regs, np.zeros((C - regs.shape[0], regs.shape[1]),
                                regs.dtype)])
        rep = NamedSharding(self.mesh, P())
        self.state = minhash.ReachState(
            mins=jax.device_put(
                jnp.asarray(mins),
                NamedSharding(self.mesh, P(CAMPAIGN_AXIS, None))),
            registers=jax.device_put(
                jnp.asarray(regs),
                NamedSharding(self.mesh, P(CAMPAIGN_AXIS, None))),
            watermark=jax.device_put(
                jnp.int32(self.state.watermark), rep),
            dropped=jax.device_put(jnp.int32(self.state.dropped), rep))
        self._reach_push()
