"""Sharded window counting: ``shard_map`` over a (data, campaign) mesh.

The reference's scale-out is a keyed network shuffle: every event is routed
to the worker owning its campaign's window state (Storm
``fieldsGrouping("campaign_id")``, ``AdvertisingTopology.java:233``; Flink
``keyBy(0)`` into ``reduce.partitions`` processors,
``AdvertisingTopologyNative.java:118-119``).  Here no event moves: each
device folds its *local* batch shard into a local count delta, and the
deltas merge with ``psum`` over ICI — the allreduce replaces the shuffle
(SURVEY.md §2, parallelism census).  Window-slot claims and the event-time
watermark merge with ``pmax``; per-shard drop counts merge with ``psum``.

Semantics are bit-identical to the single-device ``ops.windowcount.step``
(tested), because integer add/max reductions are associative and
commutative — order of partial merges cannot change any count.

Layouts (global view):
- ``counts [C, W]``     — sharded on campaign axis, replicated on data axis
- ``window_ids [W]``    — replicated (window claims are global facts)
- ``watermark/dropped`` — replicated scalars
- batch columns ``[B]`` — sharded on data axis
- ``join_table [A+1]``  — replicated (1,000 ads; tiny)

``C`` must divide by the campaign-axis size (``sharded_init_state`` pads)
and ``B`` by the data-axis size — the engines pad B itself with invalid
rows when it doesn't (``data_axis_pad``; the encoder already pads every
batch to a fixed B, so the pad is a constant tail of masked rows).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.engine.pipeline import AdAnalyticsEngine
from streambench_tpu.io.redis_schema import RedisLike
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.ops.windowcount import NEG, WindowState
from streambench_tpu.parallel.mesh import CAMPAIGN_AXIS, DATA_AXIS

try:  # jax >= 0.6 top-level export
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def pad_campaigns(num_campaigns: int, mesh: Mesh) -> int:
    """Campaign count padded up to a multiple of the campaign axis."""
    nc = mesh.shape[CAMPAIGN_AXIS]
    return ((num_campaigns + nc - 1) // nc) * nc


def data_axis_pad(batch_size: int, mesh: Mesh) -> int:
    """Invalid rows appended per batch so the data axis divides it.

    The encoder already pads every batch to a fixed B; this pads B
    itself when the configured size doesn't divide the data axis, so
    any (batch size, mesh) pair works.  Padding rows are valid=False
    everywhere they can matter — masked out of counts, the watermark
    max, and drop accounting — so results stay bit-identical to the
    unpadded engine (tested)."""
    return (-batch_size) % mesh.shape[DATA_AXIS]


def pad_data_cols(pad: int, *cols):
    """Zero-pad the trailing (batch) axis of each column by ``pad`` rows.

    A zero row is invalid in every wire form: the unpacked ``valid``
    column pads to False, and a packed word of 0 decodes to
    (ad 0, type -1, valid False) — masked everywhere."""
    out = []
    for c in cols:
        c = jnp.asarray(c)
        if pad:
            c = jnp.pad(c, ((0, 0),) * (c.ndim - 1) + ((0, pad),))
        out.append(c)
    return tuple(out)


def sharded_init_state(num_campaigns: int, window_slots: int,
                       mesh: Mesh) -> WindowState:
    """Device-placed initial state with the layouts described above."""
    C = pad_campaigns(num_campaigns, mesh)
    counts = jax.device_put(
        jnp.zeros((C, window_slots), jnp.int32),
        NamedSharding(mesh, P(CAMPAIGN_AXIS, None)))
    rep = NamedSharding(mesh, P())
    return WindowState(
        counts=counts,
        window_ids=jax.device_put(
            jnp.full((window_slots,), -1, jnp.int32), rep),
        watermark=jax.device_put(jnp.int32(0), rep),
        dropped=jax.device_put(jnp.int32(0), rep),
    )


def _shard_hist(campaign, mask, Cl: int, n_shards: int):
    """Replicated ``[S]`` histogram of ``mask`` rows by owning campaign
    shard (``campaign // Cl``).  Computed from replicated inputs with no
    ``axis_index``, so the shard_map replication checker can prove the
    result unvarying over BOTH axes — the shard-skew stats ride out as
    ``P()`` outputs with zero extra collectives."""
    shard = jnp.clip(campaign // Cl, 0, n_shards - 1)
    flat = jnp.where(mask, shard, n_shards)
    return (jnp.zeros(n_shards + 1, jnp.int32)
            .at[flat].add(1)[:n_shards])


def _fold_one(counts, window_ids, watermark, dropped, join_table,
              ad_idx, event_type, event_time, valid,
              *, divisor_ms: int, lateness_ms: int, view_type: int,
              n_data: int, stats_shards: int = 0):
    """Per-batch fold, written against shard-local views inside shard_map.
    Shared by the single-batch step and the scanned multi-batch step.

    Communication shape (the part that must ride ICI well): the BATCH is
    all-gathered across the data axis — a few hundred KB — and the
    [Cl, W] counts shard is updated by an in-place scatter-add (the jit
    wrapper donates the counts buffer, so no copy of the key space is
    ever made).  The previous formulation materialized and psum-ed a
    full [Cl, W] delta per batch, which at C=1e6 moved 256 MB per
    8k-event batch; measured on CPU the in-place form is ~1400x faster
    (0.11 ms vs 159 ms per batch).  After the gather every device sees
    the same full batch, so the slot claim and watermark are computed
    identically everywhere — replicated by construction, no pmax.
    """
    ad_idx, event_type, event_time, valid = (
        _gather_replicated(x, n_data)
        for x in (ad_idx, event_type, event_time, valid))
    valid = valid > 0
    return _fold_core(counts, window_ids, watermark, dropped, join_table,
                      ad_idx, event_type, event_time, valid,
                      divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                      view_type=view_type, stats_shards=stats_shards)


def _gather_replicated(x, n_data: int):
    """All-gather a data-axis-sharded column with a PROVABLY replicated
    int32 result: scatter the local shard into a zero [B_total] buffer
    and psum — the checker knows psum output is unvarying over the
    axis, where all_gather's output it must assume varying.  One
    [B_total] collective either way; B is KBs, the counts are the MBs
    that stay put.  (A size-1 axis still marks its inputs varying, so
    the n_data == 1 case is an identity psum that proves replication.)
    The ONE copy of this trick — both the unpacked and the packed fold
    must gather identically.  Gathers along the LAST axis, so it takes
    both the per-batch ``[b]`` column and the hoisted-scan ``[K, b]``
    stack (ONE [K, B] collective for a whole dispatch)."""
    if n_data == 1:
        return jax.lax.psum(x.astype(jnp.int32), DATA_AXIS)
    b = x.shape[-1]
    buf = jnp.zeros(x.shape[:-1] + (n_data * b,), jnp.int32)
    i = jax.lax.axis_index(DATA_AXIS)
    start = (0,) * (x.ndim - 1) + (i * b,)
    buf = jax.lax.dynamic_update_slice(buf, x.astype(jnp.int32), start)
    return jax.lax.psum(buf, DATA_AXIS)


def _fold_one_packed(counts, window_ids, watermark, dropped, join_table,
                     packed, event_time,
                     *, divisor_ms: int, lateness_ms: int, view_type: int,
                     n_data: int, stats_shards: int = 0):
    """``_fold_one`` consuming the packed wire word
    (``ops.windowcount.pack_columns``): two data-axis collectives per
    batch instead of four — the packing that halves host->device bytes
    also halves the ICI all-gather traffic (MEASURED, not just claimed:
    MULTICHIP_r06.json records packed_col_ratio 0.5 from the compiled
    HLO via ``parallel.collectives``).  Unpacks AFTER the gather, so
    every device decodes the identical replicated words."""
    packed = _gather_replicated(packed, n_data)
    event_time = _gather_replicated(event_time, n_data)
    ad_idx, event_type, valid = wc.unpack_columns(packed)
    return _fold_core(counts, window_ids, watermark, dropped, join_table,
                      ad_idx, event_type, event_time, valid,
                      divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                      view_type=view_type, stats_shards=stats_shards)


def _fold_local(counts, window_ids, watermark, join_table,
                ad_idx, event_type, event_time, valid,
                *, divisor_ms: int, lateness_ms: int, view_type: int,
                stats_shards: int = 0):
    """The collective-free shard-local fold over an already-replicated
    batch.  Returns ``(counts, ids, wm, wanted_n, counted_local)``;
    the caller merges ``counted_local`` with a campaign-axis psum —
    either per batch (``_fold_core``) or ONCE per dispatch (the hoisted
    scan: psum is linear over int32 sums, so deferring the merge is
    bit-identical).  ``stats_shards > 0`` (the obs shard-skew arm)
    appends replicated ``[S]`` per-shard (wanted, routed) row
    histograms — see :func:`_shard_hist`."""
    Cl, W = counts.shape

    campaign = join_table[ad_idx]                 # [B] gather-join
    wid = event_time // divisor_ms
    wanted = valid & (event_type == view_type) & (campaign >= 0)

    batch_max = jnp.max(jnp.where(valid, event_time, NEG))
    new_wm = jnp.maximum(watermark, batch_max)

    # Lateness vs the watermark as of batch start (see ops.windowcount).
    min_wid = (watermark - lateness_ms) // divisor_ms
    mask = wanted & (wid >= min_wid) & (wid >= 0)

    # Ring-slot claim over the full (gathered) batch: every device
    # computes the identical result from replicated inputs.
    slot = wid % W
    slot_or_pad = jnp.where(mask, slot, W)
    padded = jnp.concatenate(
        [window_ids, jnp.full((1,), -1, jnp.int32)])
    padded = padded.at[slot_or_pad].max(wid)
    new_ids = padded[:W]

    owns = new_ids[slot] == wid
    count_mask = mask & owns

    # Keyed-state routing without moving state: each device scatters the
    # full batch into its own campaign shard IN PLACE; out-of-shard rows
    # index past the buffer and drop.
    c0 = jax.lax.axis_index(CAMPAIGN_AXIS) * Cl
    local_c = campaign - c0
    in_shard = count_mask & (local_c >= 0) & (local_c < Cl)
    flat = jnp.where(in_shard, local_c * W + slot, Cl * W)
    new_counts = (counts.reshape(-1)
                  .at[flat].add(1, mode="drop")
                  .reshape(Cl, W))

    wanted_n = jnp.sum(wanted.astype(jnp.int32))
    counted_local = jnp.sum(in_shard.astype(jnp.int32))
    base = (new_counts, new_ids, new_wm, wanted_n, counted_local)
    if not stats_shards:
        return base
    # per-shard skew stats (replicated, no collectives): `wanted` rows
    # by owning shard and `count_mask` rows by owning shard — the
    # second sums to the psum'd `counted`, so drops reconcile per shard
    wanted_s = _shard_hist(campaign, wanted, Cl, stats_shards)
    routed_s = _shard_hist(campaign, count_mask, Cl, stats_shards)
    return base + (wanted_s, routed_s)


def _fold_core(counts, window_ids, watermark, dropped, join_table,
               ad_idx, event_type, event_time, valid,
               *, divisor_ms: int, lateness_ms: int, view_type: int,
               stats_shards: int = 0):
    """The shard-local fold over an already-replicated batch."""
    new_counts, new_ids, new_wm, wanted_n, counted_local, *stats = \
        _fold_local(
            counts, window_ids, watermark, join_table,
            ad_idx, event_type, event_time, valid,
            divisor_ms=divisor_ms, lateness_ms=lateness_ms,
            view_type=view_type, stats_shards=stats_shards)
    counted = jax.lax.psum(counted_local, CAMPAIGN_AXIS)
    new_dropped = dropped + wanted_n - counted
    return (new_counts, new_ids, new_wm, new_dropped) + tuple(stats)


@functools.lru_cache(maxsize=None)
def _build_step(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                view_type: int, stats: bool = False):
    """Compile-cached sharded step for one mesh + static params.
    ``stats=True`` (the obs shard-skew arm) appends two replicated
    ``[S]`` per-shard (wanted, routed) row histograms to the outputs."""

    n_data = mesh.shape[DATA_AXIS]
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0

    def body(counts, window_ids, watermark, dropped, join_table,
             ad_idx, event_type, event_time, valid):
        return _fold_one(counts, window_ids, watermark, dropped, join_table,
                         ad_idx, event_type, event_time, valid,
                         divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                         view_type=view_type, n_data=n_data,
                         stats_shards=n_stats)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P(), P(),
                  P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    # Donating the counts shard is what makes the scatter-add in place:
    # without it every batch copies the whole [Cl, W] key space.
    return jax.jit(mapped, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_scan(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                view_type: int, hoist: bool = True,
                stats: bool = False):
    """Compile-cached scanned sharded step: fold [K, B] stacked batches in
    one dispatch (the multi-device peer of ``ops.windowcount.scan_steps``).

    ``hoist=True`` (the default, what the engine dispatches) runs the
    data-axis gathers OUTSIDE the scan body: the stacked ``[K, B]``
    columns gather in ONE collective per column per dispatch, and the
    drop-counter psum merges once after the scan — (cols + 1)
    collectives per dispatch instead of K * (cols + 1).  Bit-identical:
    the gather has no carry dependence and the psum is linear
    (integer sums are exact and associative).  ``hoist=False`` keeps
    the original per-batch collectives — the measured baseline arm
    (``bench_multichip.py``) and the equivalence oracle in tests.
    ``stats=True`` (hoisted arm only) rides per-batch ``[S]`` per-shard
    (wanted, routed) histograms out of the scan ys and appends their
    dispatch sums to the outputs."""

    n_data = mesh.shape[DATA_AXIS]
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0
    if stats and not hoist:
        raise ValueError("shard stats ride the hoisted scan only")

    def body_per_batch(counts, window_ids, watermark, dropped, join_table,
                       ad_idx, event_type, event_time, valid):
        def one(carry, xs):
            c, ids, wm, dr = carry
            a, e, t, v = xs
            return _fold_one(c, ids, wm, dr, join_table, a, e, t, v,
                             divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                             view_type=view_type, n_data=n_data), None

        carry, _ = jax.lax.scan(
            one, (counts, window_ids, watermark, dropped),
            (ad_idx, event_type, event_time, valid))
        return carry

    def body_hoisted(counts, window_ids, watermark, dropped, join_table,
                     ad_idx, event_type, event_time, valid):
        ad, et, tm, va = (_gather_replicated(x, n_data)
                          for x in (ad_idx, event_type, event_time, valid))

        # Per-batch (wanted, counted_local) ride the scan's ys (a carry
        # accumulator would make the carry campaign-varying, which the
        # scan replication checker rightly rejects); int32 sums are
        # exact and associative, so summing after the scan and psum-ing
        # ONCE is bit-identical to the per-batch merges.
        def one(carry, xs):
            c, ids, wm = carry
            a, e, t, v = xs
            c, ids, wm, wn, cl, *st = _fold_local(
                c, ids, wm, join_table, a, e, t, v > 0,
                divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                view_type=view_type, stats_shards=n_stats)
            return (c, ids, wm), (wn, cl) + tuple(st)

        (c, ids, wm), ys = jax.lax.scan(
            one, (counts, window_ids, watermark), (ad, et, tm, va))
        wn, cl = ys[0], ys[1]
        new_dropped = dropped + jnp.sum(wn) - jax.lax.psum(
            jnp.sum(cl), CAMPAIGN_AXIS)
        out = (c, ids, wm, new_dropped)
        if n_stats:
            # [K, S] per-batch shard histograms -> one [S] dispatch sum
            out += (jnp.sum(ys[2], axis=0), jnp.sum(ys[3], axis=0))
        return out

    mapped = shard_map(
        body_hoisted if hoist else body_per_batch, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P(), P(),
                  P(None, DATA_AXIS), P(None, DATA_AXIS),
                  P(None, DATA_AXIS), P(None, DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    return jax.jit(mapped, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_step_packed(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                       view_type: int, stats: bool = False):
    """``_build_step`` consuming (packed, event_time) wire columns."""
    n_data = mesh.shape[DATA_AXIS]
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0

    def body(counts, window_ids, watermark, dropped, join_table,
             packed, event_time):
        return _fold_one_packed(
            counts, window_ids, watermark, dropped, join_table,
            packed, event_time, divisor_ms=divisor_ms,
            lateness_ms=lateness_ms, view_type=view_type, n_data=n_data,
            stats_shards=n_stats)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P(), P(),
                  P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    return jax.jit(mapped, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_scan_packed(mesh: Mesh, divisor_ms: int, lateness_ms: int,
                       view_type: int, hoist: bool = True,
                       stats: bool = False):
    """``_build_scan`` consuming [K, B] (packed, event_time) columns:
    2 gathers + 1 psum per dispatch hoisted, K * 3 per-batch."""
    n_data = mesh.shape[DATA_AXIS]
    n_stats = mesh.shape[CAMPAIGN_AXIS] if stats else 0
    if stats and not hoist:
        raise ValueError("shard stats ride the hoisted scan only")

    def body_per_batch(counts, window_ids, watermark, dropped, join_table,
                       packed, event_time):
        def one(carry, xs):
            c, ids, wm, dr = carry
            p, t = xs
            return _fold_one_packed(
                c, ids, wm, dr, join_table, p, t,
                divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                view_type=view_type, n_data=n_data), None

        carry, _ = jax.lax.scan(
            one, (counts, window_ids, watermark, dropped),
            (packed, event_time))
        return carry

    def body_hoisted(counts, window_ids, watermark, dropped, join_table,
                     packed, event_time):
        pk = _gather_replicated(packed, n_data)
        tm = _gather_replicated(event_time, n_data)

        def one(carry, xs):
            c, ids, wm = carry
            p, t = xs
            # unpack AFTER the gather, identically on every device;
            # per-batch elementwise work, no collectives in the body
            a, e, v = wc.unpack_columns(p)
            c, ids, wm, wn, cl, *st = _fold_local(
                c, ids, wm, join_table, a, e, t, v,
                divisor_ms=divisor_ms, lateness_ms=lateness_ms,
                view_type=view_type, stats_shards=n_stats)
            return (c, ids, wm), (wn, cl) + tuple(st)

        (c, ids, wm), ys = jax.lax.scan(
            one, (counts, window_ids, watermark), (pk, tm))
        wn, cl = ys[0], ys[1]
        new_dropped = dropped + jnp.sum(wn) - jax.lax.psum(
            jnp.sum(cl), CAMPAIGN_AXIS)
        out = (c, ids, wm, new_dropped)
        if n_stats:
            out += (jnp.sum(ys[2], axis=0), jnp.sum(ys[3], axis=0))
        return out

    mapped = shard_map(
        body_hoisted if hoist else body_per_batch, mesh=mesh,
        in_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P(), P(),
                  P(None, DATA_AXIS), P(None, DATA_AXIS)),
        out_specs=(P(CAMPAIGN_AXIS, None), P(), P(), P())
        + ((P(), P()) if stats else ()),
    )
    return jax.jit(mapped, donate_argnums=(0,))


def sharded_step(mesh: Mesh, state: WindowState, join_table: jax.Array,
                 ad_idx, event_type, event_time, valid,
                 *, divisor_ms: int = 10_000, lateness_ms: int = 60_000,
                 view_type: int = 0) -> WindowState:
    """Fold one global micro-batch into sharded state.  Pure; jits once
    per (mesh, statics, shapes)."""
    fn = _build_step(mesh, divisor_ms, lateness_ms, view_type)
    counts, ids, wm, dropped = fn(
        state.counts, state.window_ids, state.watermark, state.dropped,
        join_table, ad_idx, event_type, event_time, valid)
    return WindowState(counts, ids, wm, dropped)


class ShardedWindowEngine(AdAnalyticsEngine):
    """AdAnalyticsEngine with state + batches sharded over a device mesh.

    Drop-in: same host loop, same Redis writeback; only the device step and
    state placement change.  The campaign axis makes BASELINE config #5
    (1e6-campaign multi-tenant) fit without replicating state.
    """

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 mesh: Mesh, campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.mesh = mesh
        # A batch size the data axis doesn't divide is padded with
        # invalid rows at dispatch (data_axis_pad), never rejected: the
        # encoder already pads to a fixed B, this pads B itself.
        self._data_pad = data_axis_pad(self.batch_size, mesh)
        # Re-place state sharded (padded on the campaign axis) and the join
        # table replicated.
        self.state = sharded_init_state(
            self.encoder.num_campaigns, self.W, mesh)
        self.join_table = jax.device_put(
            jnp.asarray(self.encoder.join_table),
            NamedSharding(mesh, P()))

    def _put_state(self, counts, window_ids, watermark, dropped):
        """Checkpoint restore with mesh shardings re-applied; accepts
        snapshots from an unsharded engine by re-padding the campaign axis."""
        C = pad_campaigns(self.encoder.num_campaigns, self.mesh)
        counts = np.asarray(counts)
        if counts.shape[0] < C:
            counts = np.pad(counts, ((0, C - counts.shape[0]), (0, 0)))
        rep = NamedSharding(self.mesh, P())
        return WindowState(
            counts=jax.device_put(
                jnp.asarray(counts),
                NamedSharding(self.mesh, P(CAMPAIGN_AXIS, None))),
            window_ids=jax.device_put(jnp.asarray(window_ids), rep),
            watermark=jax.device_put(jnp.int32(watermark), rep),
            dropped=jax.device_put(jnp.int32(dropped), rep),
        )

    def _stats_on(self) -> bool:
        """Shard-skew stats arm: only when attach_obs handed over a
        ShardSkew tracker (jax.obs.shard).  The off path dispatches the
        EXACT pre-existing kernels — stats variants are separate
        compiled programs, so the default output stays byte-identical."""
        return self._obs_shard is not None

    def _note_shard(self, out) -> tuple:
        """Peel + accumulate the trailing (wanted_s, routed_s) stats
        outputs when the skew tracker is attached."""
        if self._obs_shard is None:
            return out
        self._obs_shard.note(out[-2], out[-1])
        return out[:-2]

    def _device_step(self, batch) -> None:
        stats = self._stats_on()
        if self._pack_ok:
            fn = _build_step_packed(self.mesh, self.divisor, self.lateness,
                                    0, stats)
            packed = wc.pack_columns(batch.ad_idx, batch.event_type,
                                     batch.valid)
            packed, tm = pad_data_cols(self._data_pad, packed,
                                       batch.event_time)
            counts, ids, wm, dropped = self._note_shard(fn(
                self.state.counts, self.state.window_ids,
                self.state.watermark, self.state.dropped, self.join_table,
                packed, tm))
            self.state = WindowState(counts, ids, wm, dropped)
            return
        ad, et, tm, va = pad_data_cols(
            self._data_pad, batch.ad_idx, batch.event_type,
            batch.event_time, batch.valid)
        if stats:
            fn = _build_step(self.mesh, self.divisor, self.lateness, 0,
                             True)
            counts, ids, wm, dropped = self._note_shard(fn(
                self.state.counts, self.state.window_ids,
                self.state.watermark, self.state.dropped,
                self.join_table, ad, et, tm, va))
            self.state = WindowState(counts, ids, wm, dropped)
            return
        self.state = sharded_step(
            self.mesh, self.state, self.join_table, ad, et, tm, va,
            divisor_ms=self.divisor, lateness_ms=self.lateness)

    def _device_scan(self, ad_idx, event_type, event_time, valid) -> None:
        fn = _build_scan(self.mesh, self.divisor, self.lateness, 0,
                         True, self._stats_on())
        ad_idx, event_type, event_time, valid = pad_data_cols(
            self._data_pad, ad_idx, event_type, event_time, valid)
        counts, ids, wm, dropped = self._note_shard(fn(
            self.state.counts, self.state.window_ids, self.state.watermark,
            self.state.dropped, self.join_table,
            ad_idx, event_type, event_time, valid))
        self.state = WindowState(counts, ids, wm, dropped)

    def _device_scan_packed(self, packed, event_time) -> None:
        fn = _build_scan_packed(self.mesh, self.divisor, self.lateness, 0,
                                True, self._stats_on())
        packed, event_time = pad_data_cols(self._data_pad, packed,
                                           event_time)
        counts, ids, wm, dropped = self._note_shard(fn(
            self.state.counts, self.state.window_ids, self.state.watermark,
            self.state.dropped, self.join_table, packed, event_time))
        self.state = WindowState(counts, ids, wm, dropped)

    # ------------------------------------------------------------------
    # collective-cost accounting (parallel.collectives)
    def attach_obs(self, registry, lifecycle: bool = False,
                   spans=None, occupancy=None, xfer=None,
                   shard=None) -> None:
        super().attach_obs(registry, lifecycle, spans=spans,
                           occupancy=occupancy, xfer=xfer, shard=shard)
        self._obs_reg = registry

    def collective_report(self, k: int | None = None) -> dict:
        """Per-dispatch collective op count + payload bytes of the
        compiled kernels this engine actually dispatches (packed step +
        hoisted packed scan when ``_pack_ok``), derived from the
        optimized HLO.  Compiles out of line (``lower().compile()``
        does not share the jit call cache) — call off the hot path.
        Publishes ``streambench_collective_{ops,bytes}{kernel=}``
        gauges when obs is attached."""
        from streambench_tpu.parallel import collectives

        k = int(k or self.scan_batches)
        B = self.batch_size + self._data_pad
        st = self.state
        tm = jnp.zeros((B,), jnp.int32)
        if self._pack_ok:
            step_fn = _build_step_packed(self.mesh, self.divisor,
                                         self.lateness, 0)
            step_args = (jnp.zeros((B,), jnp.int32), tm)
            scan_fn = _build_scan_packed(self.mesh, self.divisor,
                                         self.lateness, 0)
            scan_args = (jnp.zeros((k, B), jnp.int32),
                         jnp.zeros((k, B), jnp.int32))
        else:
            step_fn = _build_step(self.mesh, self.divisor, self.lateness, 0)
            step_args = (jnp.zeros((B,), jnp.int32),
                         jnp.zeros((B,), jnp.int32), tm,
                         jnp.zeros((B,), bool))
            scan_fn = _build_scan(self.mesh, self.divisor, self.lateness, 0)
            scan_args = tuple(jnp.zeros((k, B), d)
                              for d in (jnp.int32, jnp.int32, jnp.int32,
                                        bool))
        state_args = (st.counts, st.window_ids, st.watermark, st.dropped,
                      self.join_table)
        report = {
            "batch_events": self.batch_size,
            "scan_batches": k,
            "packed": bool(self._pack_ok),
            "step": collectives.report_for(
                step_fn, *state_args, *step_args),
            "scan": collectives.report_for(
                scan_fn, *state_args, *scan_args, scan_len=k),
        }
        reg = getattr(self, "_obs_reg", None)
        if reg is not None:
            collectives.publish_gauges(reg, report)
        return report
