"""Device-mesh construction for the sharded engine.

The reference scales by partitioning the stream and hash-routing keys to
stateful workers (Storm ``fieldsGrouping("campaign_id")``,
``AdvertisingTopology.java:233``; Flink ``keyBy(0)``,
``AdvertisingTopologyNative.java:118``; Spark ``reduceByKey`` shuffle,
``AdvertisingSpark.scala:95``).  The TPU-native equivalent is a 2-D
``jax.sharding.Mesh``:

- ``data`` axis — the stream partition axis (``kafka.partitions`` /
  ``map.partitions`` analog): each device folds its own slice of the
  micro-batch; partial counts merge with ``psum`` over ICI, which replaces
  the network shuffle entirely.
- ``campaign`` axis — the keyed-state partition axis (``reduce.partitions``
  analog): window-count state is sharded by campaign so multi-tenant key
  spaces (BASELINE config #5: 1e6 campaigns) never replicate.

Either axis may be size 1; ``(N,)``-shaped meshes collapse to pure data
parallelism.  Multi-host runs get the same code over DCN via
``jax.distributed`` — the mesh just spans more devices.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from streambench_tpu.config import BenchmarkConfig

DATA_AXIS = "data"
CAMPAIGN_AXIS = "campaign"


def build_mesh(data: int = 0, campaign: int = 1,
               devices: list | None = None) -> Mesh:
    """Build a ``(data, campaign)`` mesh.  ``data=0`` means "all remaining
    devices": with 8 devices and ``campaign=2`` the mesh is 4x2."""
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if campaign < 1:
        raise ValueError(f"campaign axis must be >= 1, got {campaign}")
    if data <= 0:
        if n % campaign:
            raise ValueError(f"{n} devices not divisible by campaign={campaign}")
        data = n // campaign
    need = data * campaign
    if need > n:
        raise ValueError(f"mesh {data}x{campaign} needs {need} devices, have {n}")
    grid = np.asarray(devs[:need]).reshape(data, campaign)
    return Mesh(grid, (DATA_AXIS, CAMPAIGN_AXIS))


def mesh_from_config(cfg: BenchmarkConfig, devices: list | None = None) -> Mesh:
    """Mesh from ``jax.mesh.shape``/``jax.mesh.axes`` config keys; a 1-D
    shape is treated as pure data parallelism."""
    shape = tuple(cfg.jax_mesh_shape)
    if len(shape) == 1:
        return build_mesh(data=shape[0], campaign=1, devices=devices)
    if len(shape) == 2:
        return build_mesh(data=shape[0], campaign=shape[1], devices=devices)
    raise ValueError(f"jax.mesh.shape must be 1-D or 2-D, got {shape}")
