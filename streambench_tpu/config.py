"""Benchmark configuration: one YAML loader honoring the reference keys.

The reference reads a single YAML file three different ways (Java
``Utils.findAndReadConfigFile`` at ``streaming-benchmark-common/.../Utils.java:29-63``,
Scala manual casts at ``AdvertisingSpark.scala:33-59``, Clojure keywords at
``data/src/setup/core.clj:250-257``).  Here there is exactly one loader and one
frozen dataclass; every key of ``conf/benchmarkConf.yaml:1-39`` is honored with
the reference's defaults, and engine-specific knobs for the TPU engine live
under the ``jax.*`` prefix (same style as ``storm.*`` / ``spark.*`` knobs).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping

import yaml


class ConfigError(ValueError):
    """Raised on a missing/duplicated/ill-typed configuration source."""


def _as_list(v: Any) -> list[str]:
    if v is None:
        return []
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    return [str(v)]


@dataclasses.dataclass(frozen=True)
class BenchmarkConfig:
    """Typed view of ``benchmarkConf.yaml``.

    Field-by-field provenance is the reference config
    (``conf/benchmarkConf.yaml``, line cited per field).  ``raw`` preserves
    the full key->value map so harness code can read any ad-hoc key the same
    way Flink's ``getFlinkConfs`` flattens YAML into a parameter map
    (``AdvertisingTopologyNative.java:535-550``).
    """

    # --- fork keys (file-driven micro-batch experiments) ---
    ad_to_campaign_path: str = ""          # :4
    events_path: str = ""                  # :6
    events_num: int = 10_000_000           # :30  (events.num)
    redis_hashtable: str = "t1"            # :32  (redis.hashtable)
    window_size: int = 5000                # :34  (window.size, count-based)
    shared_file: str = "/"                 # :36
    map_partitions: int = 3                # :38  (map.partitions)
    reduce_partitions: int = 1             # :39  (reduce.partitions)

    # --- pristine-YSB keys ---
    kafka_brokers: tuple[str, ...] = ("localhost",)   # :8-9
    zookeeper_servers: tuple[str, ...] = ("localhost",)  # :11-12
    kafka_port: int = 9092                 # :14
    zookeeper_port: int = 2181             # :15
    redis_host: str = "localhost"          # :16
    redis_port: int = 6379                 # (Jedis default, AdvertisingSpark.scala:177)
    kafka_topic: str = "test1"             # :17
    kafka_partitions: int = 1              # :18
    # Real-cluster opt-in (new key): a non-empty bootstrap string selects
    # the confluent-kafka adapter (io.kafka.make_broker); empty keeps the
    # hermetic file-journal broker.  The harness maps the KAFKA_BROKERS
    # env var here (the reference's firehose IS Kafka,
    # stream-bench.sh:107-115).
    kafka_bootstrap: str = ""              # kafka.bootstrap
    # Hermetic-broker opt-in (new key): route make_broker to the fake
    # Kafka cluster (io.fakekafka) instead of the file journal — with an
    # empty bootstrap the in-process cluster, with host:port a
    # FakeKafkaServer process (START_KAFKA).  Default-off: the file
    # journal stays byte-identical.
    kafka_fake: bool = False               # kafka.fake
    process_hosts: int = 1                 # :20
    process_cores: int = 4                 # :21
    storm_workers: int = 1                 # :24
    storm_ackers: int = 2                  # :25
    spark_batchtime: int = 2000            # :28

    # --- TPU-engine knobs (new; same namespacing style as storm.*/spark.*) ---
    jax_batch_size: int = 8192             # events per device micro-batch
    jax_encode_workers: int = 1            # parallel encode threads (>1 =
    #   per-thread native encoders; ctypes releases the GIL, so this
    #   scales on multi-core hosts.  Exact-count engines only — sketch
    #   engines need one consistent intern table.  Default off: the CI
    #   host is single-core)
    jax_scan_batches: int = 8              # batches folded per device dispatch
    #   (catchup mode stacks this many micro-batches and folds them in one
    #   lax.scan call, amortizing per-dispatch latency; streaming mode and
    #   engines without a scanned kernel ignore it)
    jax_buffer_timeout_ms: int = 100       # Flink bufferTimeout analog
    #   (AdvertisingTopologyNative.java:77-79: latency/throughput tradeoff)
    jax_num_campaigns: int = 100           # key cardinality (core.clj:15)
    jax_ads_per_campaign: int = 10         # core.clj:56 / JsonGenerator.java:50-51
    jax_window_slots: int = 16             # open tumbling windows kept on device
    #   (CampaignProcessorCommon.java:37 keeps a 10-window LRU)
    jax_time_divisor_ms: int = 10_000      # window length (CampaignProcessorCommon.java:28)
    jax_flush_interval_ms: int = 1000      # flusher cadence (CampaignProcessorCommon.java:41-54)
    jax_allowed_lateness_ms: int = 60_000  # generator's max late-by (core.clj:170-173)
    # Snapshot cadence: 0 = after every flush (the default; snapshots are
    # ~10 KB so this is cheap and keeps the crash-replay window to a single
    # flush).  >0 trades a longer at-least-once replay window for fewer
    # writes.
    jax_checkpoint_interval_ms: int = 0
    jax_mesh_shape: tuple[int, ...] = (1,)  # device mesh (batch axis first)
    jax_mesh_axes: tuple[str, ...] = ("data",)
    # --- staged ingest pipeline (engine.ingest; ISSUE 3) ---
    # "off" (default) keeps the serial read->encode->dispatch loop
    # byte-identical; "on" always overlaps the three stages on threads
    # with bounded queues; "auto" enables the overlap only where it can
    # pay — block-mode ingest (native encoder + poll_block reader) on a
    # multi-core host (one core just timeslices the stages).
    jax_ingest_pipeline: str = "off"
    jax_ingest_block_queue: int = 4    # bounded read-ahead: raw journal
    #   blocks the reader thread may buffer ahead of the encode stage
    #   (backpressure bound; each block is <= one scan chunk of bytes)
    jax_ingest_batch_queue: int = 4    # encoded-batch groups the encode
    #   stage may buffer ahead of device dispatch
    jax_use_native_encoder: bool = True    # C++ fast-path when the .so is built
    # --- on-device event decode (ops.devdecode; ISSUE 6) ---
    # "off" (default) keeps host encoding byte-identical; "on" ships raw
    # journal blocks to the device and does bytes->columns + view filter
    # + ad->campaign hash join + window fold inside the jitted step
    # (exact-count engines with the generator's uuid wire format only —
    # unsupported engines fall back to host encode with a warning);
    # "auto" enables it only where the measured A/B says the device arm
    # wins (bench.py records it; accelerator backends default on).
    jax_decode_device: str = "off"
    # --- production-cardinality sketch memory (ops.salsa / ops.cms;
    # ISSUE 13) ---
    # "fixed" (default) keeps the [D, Wd] int32 count-min plane
    # byte-identical; "salsa" swaps in the SALSA merge-on-overflow
    # sketch — uint8 cells + packed merge bitmaps, ~1.09 bytes/cell vs
    # 4, counters widen to 16/32 bits only where traffic lands; "auto"
    # follows the measured cms-family winner (ops.methodbench,
    # backend/cms/W<Wd>) where one exists, else stays fixed.
    jax_cms_mode: str = "fixed"
    # SALSA starting counter width: 8 (default; pairs/quads form on
    # overflow) or 16 (every pair pre-merged — fewer settles on
    # heavy-uniform streams at 2x the bytes/cell).
    jax_cms_cell_bits: int = 8
    # 1 (default) = single-stage; 2 = SF-style two-stage: a small
    # query-side sketch (width Wd/8) refreshed with post-update fat
    # estimates — heavy-hitter queries gather from the small plane;
    # the fat stage keeps update linearity for sharded psum merges
    # (single-device engines only; the sharded session engine refuses
    # stages=2 because small-stage maxima do not merge soundly).
    jax_cms_stages: int = 1
    # --- sliced sliding windows (ops.sliding; ISSUE 12) ---
    # "off" keeps the unrolled per-k sliding fold (S ring-claim passes
    # per batch); "on" forces the sliced fold — one claim + one scatter
    # into a [C, S, W] slide-bucket plane, window counts summed from S
    # live buckets only at drain time, flushed rows bit-identical;
    # "auto" (default) uses the sliced fold wherever the plane fits and
    # the measured sliding-family winner (ops.methodbench) agrees.
    jax_sliding_sliced: str = "auto"
    # --- robustness knobs (ROBUSTNESS.md; the reference has none of these:
    # a Redis outage is a Jedis stack trace and enableCheckpointing is
    # commented out, AdvertisingTopologyNative.java:81-84) ---
    jax_sink_exactly_once: bool = False    # epoch-fenced idempotent sink
    #   writeback (ROBUSTNESS.md "Exactly-once"): every flush carries a
    #   (writer_epoch, flush_seq) fence record in the same pipeline
    #   batch, resume detects unfenced post-snapshot flushes via the
    #   sink fence, and affected windows are reconciled with absolute
    #   writes from a cumulative per-window ledger.  Default off: the
    #   serial hot path stays byte-identical (no ledger, no fence reads,
    #   native array writeback intact)
    jax_sink_retry_base_ms: int = 100      # first writer backoff after a
    #   failed window writeback; doubles per consecutive failure
    jax_sink_retry_cap_ms: int = 5000      # backoff ceiling (keeps the retry
    #   cadence near the 1 Hz flush once an outage persists)
    jax_sink_dirty_cap_rows: int = 1 << 18  # retained-row high-water mark:
    #   past this the failed-write buffer is coalesced by (campaign, window)
    #   and a warning is logged; rows are NEVER dropped (dropping = silent
    #   undercount, the failure mode the retained-batch design exists to
    #   prevent)
    jax_supervisor_restarts: int = 3       # consecutive NO-PROGRESS restarts
    #   (checkpoint offset did not advance) before the supervisor gives up;
    #   restarts that advance the offset reset the count
    jax_supervisor_backoff_base_ms: int = 50   # restart backoff, doubled per
    #   consecutive crash, with jitter
    jax_supervisor_backoff_cap_ms: int = 2000  # restart backoff ceiling
    jax_deadletter_enabled: bool = False   # journal malformed events to a
    #   <topic>-deadletter topic instead of only counting them (bad_lines);
    #   off by default: the reference drops bad tuples silently
    # --- live telemetry (obs/; default-off: the hot path must stay
    # byte-identical when observability is not asked for) ---
    jax_metrics_interval_ms: int = 0       # >0 starts the MetricsSampler at
    #   this cadence, journaling snapshot records to <workdir>/metrics.jsonl
    jax_metrics_port: int = -1             # >=0 serves a localhost Prometheus
    #   text-exposition endpoint (0 = OS-assigned ephemeral port, printed
    #   at startup); <0 = no endpoint
    jax_metrics_max_bytes: int = 0         # >0 caps metrics.jsonl: a record
    #   that would push past it rotates the file to metrics.jsonl.1 first,
    #   so a week-long chaos sweep holds <= ~2x this on disk (0 = unbounded)
    # --- window-lifecycle attribution + crash flight recorder (obs/;
    # ISSUE 4 — both default-off: the serial hot path stays byte-identical
    # when neither is asked for) ---
    jax_obs_lifecycle: bool = False        # stamp each window's journey
    #   (first read, last encode, fold, flush submit, sink ack) and
    #   decompose its YSB latency into ingest/encode/fold/flush/sink
    #   segment histograms ("attribution" in metrics.jsonl;
    #   `python -m streambench_tpu.obs attribution` renders them)
    jax_obs_flightrec: bool = False        # feed a bounded postmortem ring
    #   (runner ticks, checkpoint offsets, ingest stalls, supervisor
    #   annotations) dumped to <workdir>/flight_<reason>.jsonl on crash,
    #   give_up, fatal exception, or SIGTERM
    jax_obs_flightrec_capacity: int = 512  # flight-ring record capacity
    # --- span tracing + measured occupancy + SLO gates (obs/; ISSUE 8 —
    # all default-off: the serial hot path stays byte-identical) ---
    jax_obs_spans: bool = False            # bounded thread-aware span ring
    #   (every Tracer stage span + ingest read spans), dumped as Chrome
    #   trace-event JSON <workdir>/trace_<pid>.json at exit — loadable
    #   in perfetto; flight-recorder dumps embed the last closed spans
    jax_obs_spans_capacity: int = 4096     # span-ring capacity (evictions
    #   are counted, never silent)
    jax_obs_occupancy: bool = False        # MEASURED device occupancy:
    #   1-in-N dispatches are timed to block_until_ready completion and
    #   extrapolated into streambench_device_busy_ratio + a per-dispatch
    #   device-time histogram; also arms the recompile detector
    #   (streambench_compiles_total, steady-state-zero after warmup)
    jax_obs_occupancy_sample: int = 32     # the N in 1-in-N dispatch
    #   sampling (1 = time every dispatch; bench probes only)
    jax_slo_p99_ms: int = 0                # >0: window-latency objective —
    #   a written window whose e2e latency exceeds this is "bad"; burn
    #   rate of the error budget is tracked over fast+slow windows and
    #   breaches are journaled + gauged (streambench_slo_*), with a
    #   pass/fail verdict in the RunStats close line
    jax_slo_rate_evps: int = 0             # >0: ingest-rate objective —
    #   a sample interval below this rate (while events flow) is "bad"
    jax_slo_budget: float = 0.01           # error budget: fraction of
    #   windows/intervals allowed to be bad before the burn rate hits 1
    jax_slo_fast_s: int = 30               # fast burn window (onset)
    jax_slo_slow_s: int = 180              # slow burn window (confirmation)
    # --- data-path observability (obs/; ISSUE 9 — transfer + device
    # memory ledgers, shard skew, triggered profiler capture; all
    # default-off: the hot path stays byte-identical) ---
    jax_obs_xfer: bool = False             # host->device transfer ledger:
    #   exact payload bytes per dispatch keyed by wire format (packed/
    #   unpacked/devdecode) -> streambench_xfer_* + measured bytes/event
    jax_obs_xfer_sample: int = 32          # the N in 1-in-N timed
    #   device_put+block_until_ready transfer samples (0 = bytes only)
    jax_obs_devmem: bool = False           # device-memory ledger: compiled
    #   kernel memory_analysis footprints (once, post-warmup) + a sampled
    #   jax.live_arrays census -> "devmem" block + streambench_devmem_*
    jax_obs_shard: bool = False            # per-shard routed-row/drop skew
    #   gauges for the sharded engines (streambench_shard_rows{shard=},
    #   imbalance ratio); needs --sharded
    jax_obs_capture: bool = False          # bounded TRIGGERED profiler
    #   capture: SLO breach transition / SIGUSR2 / one-shot fires a short
    #   jax.profiler window into <workdir>/xprof_<ms>_<reason>/
    jax_obs_capture_cooldown_s: float = 60.0  # min seconds between captures
    jax_obs_capture_max: int = 3           # hard cap on captures per run
    jax_obs_capture_window_s: float = 3.0  # seconds each capture records
    jax_obs_capture_oneshot: bool = False  # fire one capture at startup
    #   (smoke tests / "trace the warm ramp" runs)
    # --- live reach-query serving (reach/; ISSUE 10 — the MinHash∪HLL
    # audience-overlap engine behind the pubsub/store surface) ---
    jax_reach_k: int = 256                 # MinHash signature slots per
    #   campaign ([C, k] running minima); the overlap estimate's
    #   relative-to-union error is ~1/sqrt(k) (6.25% at the default)
    jax_reach_queue_depth: int = 512       # bounded reach-query queue:
    #   beyond this depth the OLDEST pending query is shed (answered
    #   {"shed": true}, streambench_reach_shed_total counts it)
    jax_reach_slo_p99_ms: int = 0          # >0: reach-serving latency
    #   objective — a served query slower than this (submit -> reply)
    #   is "bad"; judged by the same two-window burn-rate machinery as
    #   jax.slo.p99.ms, surfaced under objective="reach"
    # --- reach scale-out (reach/{cache,replica}; ISSUE 14 — the
    # (epoch, campaign-set) result cache + snapshot-shipped read
    # replicas) ---
    jax_reach_cache_capacity: int = 4096   # bounded LRU of query
    #   answers keyed (epoch, canonical campaign-set, kind); epoch
    #   bumps invalidate wholesale; 0 disables
    jax_reach_ship_dir: str = ""           # non-empty: ship (epoch,
    #   planes, watermark) records into <dir>/dimensions.log at the
    #   interval below — the log replica processes tail
    #   (python -m streambench_tpu.reach.replica --ship <dir>)
    jax_reach_ship_interval_ms: int = 1000  # replica shipping cadence:
    #   the replica staleness bound is cadence + poll when healthy
    jax_reach_ship_delta: str = "off"      # O(ΔC) dirty-row delta
    #   shipping (reach/deltaship; ISSUE 18): "on" ships chain-stamped
    #   delta records between periodic full bases, "auto" enables it
    #   at >= 4096 campaigns (below that the full gather is trivially
    #   cheap), "off" keeps the full-plane path
    # --- query-path observability (obs/queryattr; ISSUE 11 — the
    # serving-tier mirror of jax.obs.lifecycle; default-off: reply
    # payloads stay byte-identical) ---
    jax_obs_query: bool = False            # stamp each reach query's
    #   journey (admission, queue-exit, dispatch submit/complete, reply
    #   write) and decompose its submit->reply latency into
    #   queue/batch/dispatch/reply segment histograms
    #   (streambench_reach_segment_ms) + the ingest-contention ratio
    #   when jax.obs.spans is also on; replies gain a "server" block
    jax_obs_query_slowlog: int = 128       # slow-query log capacity:
    #   full decompositions of queries over jax.reach.slo.p99.ms,
    #   oldest-first eviction (counted, never silent)
    jax_obs_query_sample: int = 1          # 1-in-N reach dispatches
    #   additionally timed to block_until_ready for the pure device
    #   histogram (the worker materializes results synchronously, so
    #   even 1 costs only a split stamp)
    # --- fleet observability (obs/fleet + obs/clock; ISSUE 15 —
    # default-off: replica replies stay byte-identical) ---
    jax_obs_fleet: bool = False            # freshness ledger: shipped
    #   records carry fold/ship-submit wall stamps + the writer's
    #   pub/sub origin, writer-attached replies gain the freshness hop
    #   decomposition, and the metrics journal is role-stamped
    #   "writer" for the FleetCollector; replicas opt in with --fleet
    #   (replies then decompose their evidence age into
    #   fold_lag/ship_wait/tail_lag/serve hops summing to staleness_ms,
    #   with the writer clock offset estimated over the pub/sub ping
    #   verb and never applied past the jitter threshold)
    # --- multi-tenant host + admission control (engine/tenants +
    # obs/tenancy + obs/admission; ISSUE 19 — default-off: without
    # jax.tenants the single-engine path is byte-identical) ---
    jax_tenants: str = ""                  # "name:kind,..." tenant spec
    #   (kinds: exact/hll/sliding/session/reach/hllx).  Non-empty runs
    #   the MultiTenantHost: every tenant gets its own engine + a
    #   tenant= labeled view over one shared registry, and the
    #   DeviceTimeLedger attributes device time per tenant
    jax_admission_enabled: bool = False    # measurement-actuated
    #   admission control: defer/shed an aggressor tenant's ingest
    #   when the blame matrix says its dispatches burn a victim
    #   tenant's SLO budget (priming + hysteresis + cooldowns;
    #   decisions journaled with evidence)
    jax_admission_breach_ticks: int = 2    # consecutive breaching
    #   controller steps before a gate goes up (hysteresis)
    jax_admission_healthy_ticks: int = 4   # consecutive healthy steps
    #   before every gate is released
    jax_admission_escalate_ticks: int = 6  # defer-gate steps without
    #   recovery before escalating defer -> shed
    jax_admission_cooldown_s: float = 3.0  # min seconds between gate
    #   changes (breaches inside it count as holds, never actions)

    raw: Mapping[str, Any] = dataclasses.field(default_factory=dict, repr=False)

    # ------------------------------------------------------------------
    @property
    def kafka_bootstrap_servers(self) -> str | None:
        """Bootstrap string when a real cluster is opted in, else None
        (the ``io.kafka.make_broker`` switch input)."""
        return self.kafka_bootstrap or None

    @property
    def kafka_host_list(self) -> str:
        """``host:port,host:port`` string, as built at ``core.clj:252-254``."""
        return ",".join(f"{b}:{self.kafka_port}" for b in self.kafka_brokers)

    @property
    def num_ads(self) -> int:
        return self.jax_num_campaigns * self.jax_ads_per_campaign

    def get(self, key: str, default: Any = None) -> Any:
        """Raw-key access (``spark.batchtime`` style), like the JVM readers."""
        return self.raw.get(key, default)

    # ------------------------------------------------------------------
    @staticmethod
    def from_mapping(conf: Mapping[str, Any]) -> "BenchmarkConfig":
        def geti(key: str, default: int) -> int:
            v = conf.get(key, default)
            try:
                return int(v)
            except (TypeError, ValueError) as e:
                raise ConfigError(f"config key {key!r} is not an int: {v!r}") from e

        def gets(key: str, default: str) -> str:
            v = conf.get(key, default)
            return default if v is None else str(v)

        def getf(key: str, default: float) -> float:
            v = conf.get(key, default)
            try:
                return float(v)
            except (TypeError, ValueError) as e:
                raise ConfigError(
                    f"config key {key!r} is not a number: {v!r}") from e

        def getb(key: str, default: bool) -> bool:
            v = conf.get(key, default)
            if isinstance(v, bool):
                return v
            if isinstance(v, str):
                if v.lower() in ("true", "yes", "1"):
                    return True
                if v.lower() in ("false", "no", "0"):
                    return False
            if isinstance(v, int):
                return bool(v)
            raise ConfigError(f"config key {key!r} is not a bool: {v!r}")

        ingest_mode = gets("jax.ingest.pipeline", "off").strip().lower()
        if ingest_mode not in ("off", "on", "auto"):
            raise ConfigError(
                f"config key 'jax.ingest.pipeline' must be one of "
                f"off/on/auto: {ingest_mode!r}")
        decode_mode = gets("jax.decode.device", "off").strip().lower()
        if decode_mode not in ("off", "on", "auto"):
            raise ConfigError(
                f"config key 'jax.decode.device' must be one of "
                f"off/on/auto: {decode_mode!r}")
        ship_delta = gets("jax.reach.ship.delta", "off").strip().lower()
        if ship_delta not in ("off", "on", "auto"):
            raise ConfigError(
                f"config key 'jax.reach.ship.delta' must be one of "
                f"off/on/auto: {ship_delta!r}")
        sliced_mode = gets("jax.sliding.sliced", "auto").strip().lower()
        if sliced_mode not in ("off", "on", "auto"):
            raise ConfigError(
                f"config key 'jax.sliding.sliced' must be one of "
                f"off/on/auto: {sliced_mode!r}")
        cms_mode = gets("jax.cms.mode", "fixed").strip().lower()
        if cms_mode not in ("fixed", "salsa", "auto"):
            raise ConfigError(
                f"config key 'jax.cms.mode' must be one of "
                f"fixed/salsa/auto: {cms_mode!r}")
        cms_bits = geti("jax.cms.cell.bits", 8)
        if cms_bits not in (8, 16):
            raise ConfigError(
                f"config key 'jax.cms.cell.bits' must be 8 or 16: "
                f"{cms_bits!r}")
        cms_stages = geti("jax.cms.stages", 1)
        if cms_stages not in (1, 2):
            raise ConfigError(
                f"config key 'jax.cms.stages' must be 1 or 2: "
                f"{cms_stages!r}")
        mesh_shape = conf.get("jax.mesh.shape", (1,))
        mesh_axes = conf.get("jax.mesh.axes", ("data",))
        try:
            mesh_shape_t = tuple(int(x) for x in _as_list(mesh_shape)) or (1,)
        except (TypeError, ValueError) as e:
            raise ConfigError(
                f"config key 'jax.mesh.shape' is not a list of ints: {mesh_shape!r}"
            ) from e
        return BenchmarkConfig(
            ad_to_campaign_path=gets("ad_to_campaign_path", ""),
            events_path=gets("events_path", ""),
            events_num=geti("events.num", 10_000_000),
            redis_hashtable=gets("redis.hashtable", "t1"),
            window_size=geti("window.size", 5000),
            shared_file=gets("shared_file", "/"),
            map_partitions=geti("map.partitions", 3),
            reduce_partitions=geti("reduce.partitions", 1),
            kafka_brokers=tuple(_as_list(conf.get("kafka.brokers", ["localhost"]))),
            zookeeper_servers=tuple(_as_list(conf.get("zookeeper.servers", ["localhost"]))),
            kafka_port=geti("kafka.port", 9092),
            zookeeper_port=geti("zookeeper.port", 2181),
            redis_host=gets("redis.host", "localhost"),
            redis_port=geti("redis.port", 6379),
            kafka_topic=gets("kafka.topic", "test1"),
            kafka_partitions=geti("kafka.partitions", 1),
            kafka_bootstrap=gets("kafka.bootstrap", ""),
            kafka_fake=getb("kafka.fake", False),
            process_hosts=geti("process.hosts", 1),
            process_cores=geti("process.cores", 4),
            storm_workers=geti("storm.workers", 1),
            storm_ackers=geti("storm.ackers", 2),
            spark_batchtime=geti("spark.batchtime", 2000),
            jax_batch_size=geti("jax.batch.size", 8192),
            jax_encode_workers=geti("jax.encode.workers", 1),
            jax_scan_batches=geti("jax.scan.batches", 8),
            jax_buffer_timeout_ms=geti("jax.buffer.timeout.ms", 100),
            jax_num_campaigns=geti("jax.num.campaigns", 100),
            jax_ads_per_campaign=geti("jax.ads.per.campaign", 10),
            jax_window_slots=geti("jax.window.slots", 16),
            jax_time_divisor_ms=geti("jax.time.divisor.ms", 10_000),
            jax_flush_interval_ms=geti("jax.flush.interval.ms", 1000),
            jax_allowed_lateness_ms=geti("jax.allowed.lateness.ms", 60_000),
            jax_checkpoint_interval_ms=geti("jax.checkpoint.interval.ms", 0),
            jax_mesh_shape=mesh_shape_t,
            jax_mesh_axes=tuple(_as_list(mesh_axes)) or ("data",),
            jax_ingest_pipeline=ingest_mode,
            jax_ingest_block_queue=max(geti("jax.ingest.block.queue", 4), 1),
            jax_ingest_batch_queue=max(geti("jax.ingest.batch.queue", 4), 1),
            jax_use_native_encoder=getb("jax.use.native.encoder", True),
            jax_decode_device=decode_mode,
            jax_cms_mode=cms_mode,
            jax_cms_cell_bits=cms_bits,
            jax_cms_stages=cms_stages,
            jax_sliding_sliced=sliced_mode,
            jax_sink_exactly_once=getb("jax.sink.exactly_once", False),
            jax_sink_retry_base_ms=geti("jax.sink.retry.base.ms", 100),
            jax_sink_retry_cap_ms=geti("jax.sink.retry.cap.ms", 5000),
            jax_sink_dirty_cap_rows=geti("jax.sink.dirty.cap.rows", 1 << 18),
            jax_supervisor_restarts=geti("jax.supervisor.restarts", 3),
            jax_supervisor_backoff_base_ms=geti(
                "jax.supervisor.backoff.base.ms", 50),
            jax_supervisor_backoff_cap_ms=geti(
                "jax.supervisor.backoff.cap.ms", 2000),
            jax_deadletter_enabled=getb("jax.deadletter.enabled", False),
            jax_metrics_interval_ms=geti("jax.metrics.interval.ms", 0),
            jax_metrics_port=geti("jax.metrics.port", -1),
            jax_metrics_max_bytes=geti("jax.metrics.max.bytes", 0),
            jax_obs_lifecycle=getb("jax.obs.lifecycle", False),
            jax_obs_flightrec=getb("jax.obs.flightrec.enabled", False),
            jax_obs_flightrec_capacity=max(
                geti("jax.obs.flightrec.capacity", 512), 8),
            jax_obs_spans=getb("jax.obs.spans", False),
            jax_obs_spans_capacity=max(
                geti("jax.obs.spans.capacity", 4096), 16),
            jax_obs_occupancy=getb("jax.obs.occupancy", False),
            jax_obs_occupancy_sample=max(
                geti("jax.obs.occupancy.sample", 32), 1),
            jax_slo_p99_ms=max(geti("jax.slo.p99.ms", 0), 0),
            jax_slo_rate_evps=max(geti("jax.slo.rate.evps", 0), 0),
            jax_slo_budget=getf("jax.slo.budget", 0.01),
            jax_slo_fast_s=max(geti("jax.slo.window.fast.s", 30), 1),
            jax_slo_slow_s=max(geti("jax.slo.window.slow.s", 180), 1),
            jax_obs_xfer=getb("jax.obs.xfer", False),
            jax_obs_xfer_sample=max(geti("jax.obs.xfer.sample", 32), 0),
            jax_obs_devmem=getb("jax.obs.devmem", False),
            jax_obs_shard=getb("jax.obs.shard", False),
            jax_obs_capture=getb("jax.obs.capture.enabled", False),
            jax_obs_capture_cooldown_s=max(
                getf("jax.obs.capture.cooldown.s", 60.0), 0.0),
            jax_obs_capture_max=max(geti("jax.obs.capture.max", 3), 1),
            jax_obs_capture_window_s=max(
                getf("jax.obs.capture.window.s", 3.0), 0.1),
            jax_obs_capture_oneshot=getb("jax.obs.capture.oneshot", False),
            jax_reach_k=max(geti("jax.reach.k", 256), 1),
            jax_reach_queue_depth=max(
                geti("jax.reach.queue.depth", 512), 1),
            jax_reach_slo_p99_ms=max(geti("jax.reach.slo.p99.ms", 0), 0),
            jax_reach_cache_capacity=max(
                geti("jax.reach.cache.capacity", 4096), 0),
            jax_reach_ship_dir=gets("jax.reach.ship.dir", ""),
            jax_reach_ship_interval_ms=max(
                geti("jax.reach.ship.interval.ms", 1000), 1),
            jax_reach_ship_delta=ship_delta,
            jax_obs_query=getb("jax.obs.query", False),
            jax_obs_fleet=getb("jax.obs.fleet", False),
            jax_obs_query_slowlog=max(
                geti("jax.obs.query.slowlog", 128), 1),
            jax_obs_query_sample=max(geti("jax.obs.query.sample", 1), 1),
            jax_tenants=gets("jax.tenants", ""),
            jax_admission_enabled=getb("jax.admission.enabled", False),
            jax_admission_breach_ticks=max(
                geti("jax.admission.breach.ticks", 2), 1),
            jax_admission_healthy_ticks=max(
                geti("jax.admission.healthy.ticks", 4), 1),
            jax_admission_escalate_ticks=max(
                geti("jax.admission.escalate.ticks", 6), 1),
            jax_admission_cooldown_s=max(
                getf("jax.admission.cooldown.s", 3.0), 0.0),
            raw=dict(conf),
        )


def find_and_read_config_file(path: str | os.PathLike[str]) -> BenchmarkConfig:
    """Load a YAML config from the filesystem.

    Mirrors ``Utils.findAndReadConfigFile`` (``Utils.java:29-63``): the file
    must exist, parse as a YAML mapping, and be non-empty; any failure raises
    rather than silently proceeding.
    """
    path = os.fspath(path)
    if not os.path.exists(path):
        raise ConfigError(f"config file not found: {path}")
    with open(path, "r", encoding="utf-8") as f:
        try:
            data = yaml.safe_load(f)
        except yaml.YAMLError as e:
            raise ConfigError(f"config file is not valid YAML: {path}: {e}") from e
    if data is None:
        raise ConfigError(f"config file is empty: {path}")
    if not isinstance(data, dict):
        raise ConfigError(f"config file is not a YAML mapping: {path}")
    return BenchmarkConfig.from_mapping(data)


def load_config_or_default(path: str | os.PathLike[str], *,
                           is_default_path: bool) -> "BenchmarkConfig":
    """CLI convention shared by the datagen/handoff entry points: a
    MISSING file at the parser's DEFAULT path falls back to built-in
    defaults (hermetic runs need no config file), while an explicitly
    given path must exist.  Parse errors always raise ``ConfigError``."""
    import sys

    path = os.fspath(path)
    if is_default_path and not os.path.exists(path):
        print(f"note: config file not found: {path}; using built-in "
              "defaults", file=sys.stderr)
        return default_config()
    return find_and_read_config_file(path)


def default_config(**overrides: Any) -> BenchmarkConfig:
    """A config with the checked-in ``benchmarkConf.yaml`` defaults.

    ``overrides`` use dataclass field names (``redis_port=...``), mainly for
    tests and embedded runs.
    """
    base = BenchmarkConfig.from_mapping({})
    return dataclasses.replace(base, **overrides) if overrides else base


def write_local_conf(path: str | os.PathLike[str], conf: Mapping[str, Any]) -> None:
    """Generate a ``localConf.yaml``, as SETUP does (``stream-bench.sh:123-138``)."""
    with open(path, "w", encoding="utf-8") as f:
        yaml.safe_dump(dict(conf), f, default_flow_style=False, sort_keys=True)
