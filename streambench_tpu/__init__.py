"""streambench_tpu — a TPU-native streaming-benchmark framework.

A from-scratch re-design of the Yahoo Streaming Benchmark capability set
(reference: francis0407/streaming-benchmarks) for TPU hardware:

- the ad-analytics pipeline (deserialize -> filter "view" -> project ->
  join ad->campaign -> count per (campaign, 10s window) -> Redis writeback,
  per ``README.markdown:33-37`` of the reference) is executed as an
  XLA-compiled micro-batch scan: events are int-encoded on the host into
  fixed-shape columnar batches and aggregated with masked segment-sums
  carried through ``jax.lax.scan``;
- sketch variants (HyperLogLog, count-min, t-digest) replace the exact
  count as pure-array aggregation kernels whose merges are psum-shaped,
  so multi-device scale-out over an ICI mesh is a sharding annotation,
  not a rewrite;
- the harness contract of the reference is preserved: the same
  ``benchmarkConf.yaml`` keys (``conf/benchmarkConf.yaml:1-39``), the same
  canonical Redis output schema (``AdvertisingSpark.scala:184-208``), the
  same generator/oracle modes (``data/src/setup/core.clj:259-286``), and a
  ``stream-bench.sh``-compatible operation grammar.

Layout (mirrors SURVEY.md section 7's build plan):

- ``config``     — YAML config honoring every reference key
- ``io``         — RESP client, fake Redis, canonical schema, journal broker
- ``datagen``    — load generator + golden-model oracle (core.clj peer)
- ``encode``     — host-side string->int32 interning and batch staging
- ``ops``        — aggregation kernels (window counts, HLL, count-min, t-digest)
- ``engine``     — window state carry, jitted step, scan, runner, flusher
- ``models``     — the five benchmark topologies from BASELINE.json
- ``parallel``   — mesh construction and shard_map'd multi-device step
- ``metrics``    — stamped-timestamp tracing and latency decile reports
- ``harness``    — stream-bench-compatible CLI operations
"""

__version__ = "0.1.0"
