from streambench_tpu.datagen.gen import (  # noqa: F401
    AD_TYPES,
    EVENT_TYPES,
    EventSource,
    check_correct,
    do_new_setup,
    do_setup,
    dostats,
    get_stats,
    run_paced,
)
