"""CLI for the generator/oracle — flag-compatible with the reference
(``cli-options``, ``core.clj:259-271``):

    python -m streambench_tpu.datagen -n  --configPath conf.yaml
    python -m streambench_tpu.datagen -r -t 1000 [-w] --configPath conf.yaml
    python -m streambench_tpu.datagen -g  --configPath conf.yaml
    python -m streambench_tpu.datagen -s  --configPath conf.yaml
    python -m streambench_tpu.datagen -c  --configPath conf.yaml

Extra (new-framework) flags: ``--brokerDir`` (file-broker directory; defaults
next to the workdir), ``--duration`` / ``--maxEvents`` bounds for ``-r``, and
``--workdir`` for the id/journal files (reference uses the cwd).
"""

from __future__ import annotations

import argparse
import sys

from streambench_tpu.config import ConfigError, load_config_or_default
from streambench_tpu.datagen import gen
from streambench_tpu.io.kafka import make_broker
from streambench_tpu.io.resp import RespClient


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="streambench-datagen")
    p.add_argument("-s", "--setup", action="store_true",
                   help="Set up for catchup-simulation-mode")
    p.add_argument("-c", "--check", action="store_true",
                   help="Check catchup-mode data was processed correctly")
    p.add_argument("-n", "--new", action="store_true",
                   help="Set up redis for a new real-time simulation")
    p.add_argument("--reuse-ids", action="store_true",
                   help="with -n: seed from the workdir's existing "
                        "campaign/ad id files instead of regenerating "
                        "(checkpoint resume: snapshots and journaled "
                        "events are keyed to those ids)")
    p.add_argument("-r", "--run", action="store_true",
                   help="Emit events to the broker at a fixed frequency")
    p.add_argument("-t", "--throughput", type=int, default=0,
                   help="events/sec for -r")
    p.add_argument("-w", "--with-skew", action="store_true",
                   help="Add minor skew and late tuples into the mix")
    p.add_argument("-g", "--get-stats", action="store_true",
                   help="Collect end-to-end latency stats from redis")
    p.add_argument("-a", "--configPath", default="./benchmarkConf.yaml")
    p.add_argument("--workdir", default=".")
    p.add_argument("--brokerDir", default=None)
    p.add_argument("--duration", type=float, default=None,
                   help="seconds to run -r for (default: until killed)")
    p.add_argument("--partition", type=int, default=0,
                   help="broker partition -r writes to (several generator "
                        "processes can shard one paced load across "
                        "partitions, like parallel Kafka producers)")
    p.add_argument("--maxEvents", type=int, default=None)
    p.add_argument("--users", type=int, default=100,
                   help="paced-mode user-id universe (default 100, the "
                        "reference's; session workloads need a larger "
                        "one for inter-arrival gaps to exceed the "
                        "session gap)")
    p.add_argument("--eventsNum", type=int, default=None,
                   help="override events.num for -s")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    parser_default = build_parser().get_default("configPath")
    try:
        cfg = load_config_or_default(
            args.configPath,
            is_default_path=args.configPath == parser_default)
    except ConfigError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    broker = make_broker(cfg.kafka_bootstrap_servers,
                         args.brokerDir or f"{args.workdir}/broker",
                         fake=cfg.kafka_fake)

    def redis():
        if cfg.redis_host == ":inprocess:":
            # An in-process store cannot survive across CLI invocations, so
            # -n/-g/-c against it would silently see an empty database.
            print("error: redis.host ':inprocess:' is only valid for "
                  "embedded runs, not the datagen CLI", file=sys.stderr)
            raise SystemExit(2)
        return RespClient(cfg.redis_host, cfg.redis_port)

    if args.setup and args.check:
        print("Specify either --setup OR --check")
        return 2
    if args.setup:
        n = gen.do_setup(redis(), cfg, broker=broker,
                         events_num=args.eventsNum,
                         num_campaigns=cfg.jax_num_campaigns,
                         ads_per_campaign=cfg.jax_ads_per_campaign,
                         workdir=args.workdir,
                         # one broker partition per kafka.partition, so a
                         # count-windowed consumer (map.partitions) can
                         # align with the dataset (stream-bench.sh:107-115)
                         partitions=max(cfg.kafka_partitions, 1),
                         progress=lambda k: print(k, flush=True)
                         if k % 1_000_000 == 0 else None)
        print(f"wrote {n} events")
    elif args.check:
        correct, differ, missing = gen.check_correct(
            redis(), workdir=args.workdir,
            time_divisor_ms=cfg.jax_time_divisor_ms)
        print(f"CORRECT={correct} DIFFER={differ} MISSING={missing}")
        return 0 if differ == 0 and missing == 0 else 1
    elif args.new:
        if args.reuse_ids and gen.do_reseed(redis(),
                                            workdir=args.workdir):
            print("Writing campaigns data to Redis (existing ids).")
        else:
            gen.do_new_setup(redis(), num_campaigns=cfg.jax_num_campaigns,
                             ads_per_campaign=cfg.jax_ads_per_campaign,
                             workdir=args.workdir)
            print("Writing campaigns data to Redis.")
    elif args.run:
        if args.throughput <= 0:
            print("-r requires -t THROUGHPUT > 0")
            return 2
        print(f"Running, emitting {args.throughput} tuples per second.",
              flush=True)
        # STOP_LOAD kills the generator with SIGTERM (stream-bench.sh:231);
        # exit through SystemExit so the journal writer context flushes.
        import signal

        def _term(*_):
            raise SystemExit(0)

        signal.signal(signal.SIGTERM, _term)
        broker.create_topic(cfg.kafka_topic,
                            max(cfg.kafka_partitions, args.partition + 1))
        with broker.writer(cfg.kafka_topic, args.partition) as sink:
            sent = gen.run_paced(
                sink, args.throughput, duration_s=args.duration,
                max_events=args.maxEvents, with_skew=args.with_skew,
                workdir=args.workdir, num_users=args.users,
                on_behind=lambda ms: print(f"Falling behind by: {ms:.0f}ms"),
            )
        print(f"emitted {sent} events")
    elif args.get_stats:
        stats = gen.get_stats(redis(), workdir=args.workdir)
        print(f"collected {len(stats)} windows")
    else:
        build_parser().print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
