"""Load generator + golden-model oracle: the peer of ``data/src/setup/core.clj``.

All five CLI modes of the reference generator are reimplemented
(``core.clj:259-286``): ``-n`` seed Redis, ``-r -t N`` paced real-time
emission, ``-g`` stats collection, ``-s`` catchup-dataset setup, ``-c``
golden-model correctness check.  The event wire format is byte-compatible
with ``make-kafka-event-at`` (``core.clj:163-181``): a JSON object with
``user_id/page_id/ad_id/ad_type/event_type/event_time/ip_address``, where
``event_time`` is a stringified ms timestamp.

Deliberate fixes over the fork (capabilities, not bugs, are ported):

- ``load-ids`` returning nil (``core.clj:36-45`` ends with a ``println``) is
  fixed: ids actually load from the id files.
- pacing emits due events in batches (one C-formatted block per loop pass,
  parking in a tick sleep only when nothing is due) instead of one
  ``Thread/sleep`` per event, so the generator paces hundreds of thousands
  of events/s on one core; the ">100 ms behind" warning is kept
  (``core.clj:200-202``).
"""

from __future__ import annotations

import ctypes as _ctypes
import json
import os
import random
import time

import numpy as _np
from dataclasses import dataclass
from typing import Callable, Iterable

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.io.journal import FileBroker, JournalWriter
from streambench_tpu.io.redis_schema import (
    RedisLike,
    read_seen_counts,
    read_stats,
    seed_ad_mapping,
    seed_campaigns,
)
from streambench_tpu.utils.ids import make_ids, now_ms

# Wire-format constants are shared with the encoder: generator emission and
# device-side view_type must index the same tuples or counts silently zero.
from streambench_tpu.encode.encoder import AD_TYPES, EVENT_TYPES

# id-file names, exactly as the reference writes them (core.clj:24-33,47-59)
CAMPAIGN_IDS_FILE = "campaign-ids.txt"
AD_IDS_FILE = "ad-ids.txt"
AD_TO_CAMPAIGN_FILE = "ad-to-campaign-ids.txt"
KAFKA_JSON_FILE = "kafka-json.txt"
SEEN_FILE = "seen.txt"
UPDATED_FILE = "updated.txt"


# ----------------------------------------------------------------------
# id management
# ----------------------------------------------------------------------

def write_ids(campaigns: list[str], ads: list[str], workdir: str = ".") -> None:
    """``write-ids`` (``core.clj:24-33``)."""
    with open(os.path.join(workdir, CAMPAIGN_IDS_FILE), "w") as f:
        f.write("".join(c + "\n" for c in campaigns))
    with open(os.path.join(workdir, AD_IDS_FILE), "w") as f:
        f.write("".join(a + "\n" for a in ads))


def load_ids(workdir: str = ".") -> tuple[list[str], list[str]] | None:
    """``load-ids`` with the nil-return bug fixed (``core.clj:36-45``)."""
    try:
        with open(os.path.join(workdir, CAMPAIGN_IDS_FILE)) as f:
            campaigns = [l.strip() for l in f if l.strip()]
        with open(os.path.join(workdir, AD_IDS_FILE)) as f:
            ads = [l.strip() for l in f if l.strip()]
        return campaigns, ads
    except FileNotFoundError:
        return None


def write_ad_mapping_file(campaigns: list[str], ads: list[str],
                          workdir: str = ".") -> dict[str, str]:
    """``write-to-redis``'s journal side (``core.clj:47-59``): one JSON object
    ``{"<ad>": "<campaign>"}`` per line; returns the mapping."""
    per = len(ads) // len(campaigns)
    mapping: dict[str, str] = {}
    with open(os.path.join(workdir, AD_TO_CAMPAIGN_FILE), "w") as f:
        for i, campaign in enumerate(campaigns):
            for ad in ads[i * per : (i + 1) * per]:
                mapping[ad] = campaign
                f.write(json.dumps({ad: campaign}) + "\n")
    return mapping


def load_ad_mapping_file(path: str) -> dict[str, str]:
    """Read ``ad-to-campaign-ids.txt`` (JSON-object-per-line) **or** the fork's
    CSV format ``ad,campaign`` (``getAdCampaignMap``,
    ``AdvertisingTopologyNative.java:47-56``)."""
    mapping: dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith("{"):
                mapping.update(json.loads(line))
            else:
                ad, _, campaign = line.partition(",")
                mapping[ad.strip()] = campaign.strip()
    return mapping


# ----------------------------------------------------------------------
# event synthesis
# ----------------------------------------------------------------------

@dataclass
class EventSource:
    """Synthesizes wire-format ad events (``make-kafka-event-at``,
    ``core.clj:163-181``)."""

    ads: list[str]
    user_ids: list[str]
    page_ids: list[str]
    with_skew: bool = False
    rng: random.Random | None = None

    def __post_init__(self) -> None:
        if self.rng is None:
            self.rng = random.Random()

    def event_at(self, t_ms: int) -> str:
        rng = self.rng
        t = t_ms
        if self.with_skew:
            t += 50 - rng.randrange(100)           # ±50 ms skew
            if rng.randrange(100_000) == 0:        # 1/100k late by ≤60 s
                t -= rng.randrange(60_000)
        return (
            '{"user_id": "%s", "page_id": "%s", "ad_id": "%s", '
            '"ad_type": "%s", "event_type": "%s", "event_time": "%d", '
            '"ip_address": "1.2.3.4"}'
            % (
                rng.choice(self.user_ids),
                rng.choice(self.page_ids),
                rng.choice(self.ads),
                rng.choice(AD_TYPES),
                rng.choice(EVENT_TYPES),
                t,
            )
        )

    def events_at(self, ts_ms: Iterable[int]) -> list[str]:
        return [self.event_at(t) for t in ts_ms]

    # -- native fast path -------------------------------------------------
    # The Python formatter costs ~3 us/event; on a single-core host a paced
    # producer at 100k ev/s would then eat a third of the core the engine
    # under test needs.  The C formatter (native/gen.cpp) renders the same
    # wire format at ~50 ns/event.  RNG streams differ (splitmix64 vs
    # Python's) — irrelevant to correctness: the oracle replays the journal,
    # so only the distributions are contractual (core.clj:163-181).

    def _native_ctx(self):
        if getattr(self, "_nat", None) is None:
            from streambench_tpu import native as _native

            lib = _native.load()
            if lib is None or not all(
                    len(x) == len(self.ads[0]) for x in self.ads):
                self._nat = False
                return False
            ulen = len(self.user_ids[0])
            plen = len(self.page_ids[0])
            if (not all(len(u) == ulen for u in self.user_ids)
                    or not all(len(p) == plen for p in self.page_ids)):
                self._nat = False
                return False
            at_lens = _np.asarray([len(t) for t in AD_TYPES], _np.int32)
            et_lens = _np.asarray([len(t) for t in EVENT_TYPES], _np.int32)
            per_event = lib.sb_format_events_cap(
                ulen, plen, len(self.ads[0]),
                at_lens.ctypes.data_as(_ctypes.POINTER(_ctypes.c_int32)),
                len(AD_TYPES),
                et_lens.ctypes.data_as(_ctypes.POINTER(_ctypes.c_int32)),
                len(EVENT_TYPES))
            self._nat = dict(
                lib=lib,
                users="".join(self.user_ids).encode(), ulen=ulen,
                pages="".join(self.page_ids).encode(), plen=plen,
                ads="".join(self.ads).encode(), alen=len(self.ads[0]),
                at="".join(AD_TYPES).encode(), at_lens=at_lens,
                et="".join(EVENT_TYPES).encode(), et_lens=et_lens,
                # pointers cached once: data_as costs ~2 us/call, paid
                # per paced tick otherwise (arrays are kept alive by the
                # at_lens/et_lens entries above)
                at_lens_p=at_lens.ctypes.data_as(
                    _ctypes.POINTER(_ctypes.c_int32)),
                et_lens_p=et_lens.ctypes.data_as(
                    _ctypes.POINTER(_ctypes.c_int32)),
                per_event=int(per_event),
                state=_ctypes.c_uint64(self.rng.getrandbits(64)),
                # persistent output buffer: create_string_buffer would
                # zero-fill (a hidden memset of the whole capacity) on
                # every call
                buf=_np.empty(0, _np.uint8),
            )
        return self._nat

    def events_blob_at(self, ts_ms: "Iterable[int]") -> bytes | None:
        """Render events as ONE newline-terminated byte block via the
        native formatter; None when the native library is unavailable
        (callers fall back to ``events_at``)."""
        mv = self.events_blob_view(ts_ms)
        return None if mv is None else bytes(mv)

    def events_blob_view(self, ts_ms) -> "memoryview | None":
        """Zero-copy variant of ``events_blob_at``: a memoryview over the
        source's internal buffer, valid until the NEXT call.  The paced
        producer writes it straight to the journal — the bytes() copy
        was a measurable share of producer CPU at high rates."""
        ctx = self._native_ctx()
        if not ctx:
            return None
        ts = (ts_ms if isinstance(ts_ms, _np.ndarray)
              else _np.fromiter(ts_ms, dtype=_np.int64))
        ts = _np.ascontiguousarray(ts, dtype=_np.int64)
        if ts.size == 0:
            return memoryview(b"")
        cap = int(ts.size) * ctx["per_event"]
        if ctx["buf"].size < cap:
            ctx["buf"] = _np.empty(cap, _np.uint8)
        out = ctx["buf"]
        n = ctx["lib"].sb_format_events(
            ctx["users"], ctx["ulen"], len(self.user_ids),
            ctx["pages"], ctx["plen"], len(self.page_ids),
            ctx["ads"], ctx["alen"], len(self.ads),
            ctx["at"], ctx["at_lens_p"], len(AD_TYPES),
            ctx["et"], ctx["et_lens_p"], len(EVENT_TYPES),
            ts.ctypes.data_as(_ctypes.POINTER(_ctypes.c_int64)), ts.size,
            _ctypes.byref(ctx["state"]), 1 if self.with_skew else 0,
            _ctypes.cast(out.ctypes.data, _ctypes.c_char_p), cap)
        if n < 0:
            return None
        return out.data[:n]


# ----------------------------------------------------------------------
# modes
# ----------------------------------------------------------------------

def do_new_setup(r: RedisLike, num_campaigns: int = 100,
                 ads_per_campaign: int = 10,
                 rng: random.Random | None = None,
                 workdir: str = ".") -> list[str]:
    """``-n``: flush Redis, seed the campaigns set (``core.clj:206-213``);
    also writes the id files so a following ``-r`` can load them."""
    campaigns = make_ids(num_campaigns, rng)
    seed_campaigns(r, campaigns)
    ads = make_ids(num_campaigns * ads_per_campaign, rng)
    write_ids(campaigns, ads, workdir)
    mapping = write_ad_mapping_file(campaigns, ads, workdir)
    seed_ad_mapping(r, mapping)
    return campaigns


def do_reseed(r: RedisLike, workdir: str = ".") -> list[str] | None:
    """Re-seed Redis from the EXISTING workdir id files — the
    checkpoint-resume path.  A resumed engine's snapshot (window state,
    sketch rows) and the journaled events are keyed to these exact ids;
    regenerating them (``do_new_setup``) would silently unkey both: every
    replayed event's ad would join to campaign -1 and the resumed run
    would fold empty windows.  Returns None when no id files exist (the
    caller falls back to a fresh ``do_new_setup``)."""
    ids = load_ids(workdir)
    if ids is None:
        return None
    campaigns, ads = ids
    seed_campaigns(r, campaigns)
    mapping = write_ad_mapping_file(campaigns, ads, workdir)
    seed_ad_mapping(r, mapping)
    return campaigns


def do_setup(r: RedisLike | None, cfg: BenchmarkConfig,
             broker: FileBroker | None = None,
             events_num: int | None = None,
             num_campaigns: int = 100,
             ads_per_campaign: int = 10,
             rng: random.Random | None = None,
             workdir: str = ".",
             topic: str | None = None,
             partitions: int = 1,
             progress: Callable[[int], None] | None = None) -> int:
    """``-s``: catchup-simulation setup (``do-setup`` + ``write-to-kafka``,
    ``core.clj:60-98,239-248``).

    Generates ``events_num`` events at 10 ms spacing (``core.clj:94``:
    ``event_time = start + 10*n``), journals every event to
    ``kafka-json.txt``, and appends them to the broker topic when one is
    given.  Seeds Redis (campaigns + join table) when ``r`` is given.
    Returns the number of events written.
    """
    rng = rng or random.Random()
    n_events = int(events_num if events_num is not None else cfg.events_num)
    ids = load_ids(workdir)
    if ids is None:
        campaigns = make_ids(num_campaigns, rng)
        ads = make_ids(num_campaigns * ads_per_campaign, rng)
        write_ids(campaigns, ads, workdir)
    else:
        campaigns, ads = ids
    mapping = write_ad_mapping_file(campaigns, ads, workdir)
    if r is not None:
        seed_campaigns(r, campaigns)
        seed_ad_mapping(r, mapping)

    src = EventSource(
        ads=ads,
        user_ids=make_ids(100, rng),
        page_ids=make_ids(100, rng),
        with_skew=False,
        rng=rng,
    )
    start = now_ms()
    topic = topic or cfg.kafka_topic
    # Truncate the topic alongside the journal: -s defines a fresh dataset,
    # and oracle (kafka-json.txt) and topic must stay in lockstep.
    # One writer per topic partition, round-robin by event index — the
    # broker peer of `create_kafka_topic --partitions $PARTITIONS`
    # (stream-bench.sh:107-115); partition counts stay equal whenever
    # n_events divides evenly, which count-windowed consumers rely on.
    sinks = ([broker.writer(topic, p, append=False)
              for p in range(partitions)] if broker is not None else [])
    written = 0
    # Single-partition fast path: the native formatter renders each batch
    # as one byte block shared by journal and topic (multi-partition keeps
    # the line path — round-robin slicing needs per-event boundaries).
    blob_ok = len(sinks) <= 1 and all(
        hasattr(s, "append_bytes") for s in sinks)
    with open(os.path.join(workdir, KAFKA_JSON_FILE), "wb") as journal:
        batch = 100_000
        for base in range(0, n_events, batch):
            hi = min(base + batch, n_events)
            ts = start + 10 * _np.arange(base, hi, dtype=_np.int64)
            blob = src.events_blob_at(ts) if blob_ok else None
            if blob is not None:
                journal.write(blob)
                if sinks:
                    sinks[0].append_bytes(blob)
            else:
                lines = src.events_at(
                    start + 10 * n for n in range(base, hi))
                journal.write(b"".join(
                    l.encode() + b"\n" for l in lines))
                if sinks:
                    if len(sinks) == 1:
                        sinks[0].append_many(lines)
                    else:
                        for p, sink in enumerate(sinks):
                            off = (p - base) % len(sinks)
                            sink.append_many(lines[off::len(sinks)])
            written = hi
            if progress:
                progress(written)
    for sink in sinks:
        sink.close()
    return written


def run_paced(sink: JournalWriter, throughput: int,
              duration_s: float | None = None,
              max_events: int | None = None,
              with_skew: bool = False,
              workdir: str = ".",
              rng: random.Random | None = None,
              tick_s: float = 0.01,
              num_users: int = 100,
              on_behind: Callable[[float], None] | None = None) -> int:
    """``-r -t N``: paced emission at ``throughput`` events/s (``run``,
    ``core.clj:183-204``).

    Event ``n`` is scheduled at ``start + n/throughput`` and carries that
    scheduled time as its ``event_time`` — exactly the reference's pacing
    contract (``times`` lazy seq, ``core.clj:190-191``).  Events due in the
    same ~10 ms tick are emitted as one batch, which is what lets a single
    Python process sustain rates the per-event-sleep Clojure loop cannot.
    Returns events emitted.  Stops after ``duration_s`` or ``max_events``.
    """
    ids = load_ids(workdir)
    if ids is None:
        raise FileNotFoundError(
            f"id files not found in {workdir!r}; run -n (new setup) first")
    _, ads = ids
    rng = rng or random.Random()
    src = EventSource(ads=ads, user_ids=make_ids(num_users, rng),
                      page_ids=make_ids(100, rng), with_skew=with_skew,
                      rng=rng)

    period_ns = int(1e9 / throughput)
    # Blob mode: native formatter renders the tick's batch as one byte
    # block straight into the journal (no per-event Python objects) —
    # essential when producer and engine share one core.
    blob_ok = hasattr(sink, "append_bytes")
    last_path = None
    start_ns = time.time_ns()
    sent = 0
    # Stall forensics: the longest single emit and the longest gap
    # between loop iterations (scheduler starvation / oversleep) tell a
    # failing sweep rung WHERE its producer lag came from.
    max_emit_ms = 0.0
    max_gap_ms = 0.0
    last_loop_ns = start_ns
    sub_max = {"ts": 0.0, "fmt": 0.0, "write": 0.0, "flush": 0.0}
    slept = True
    try:
        while True:
            if max_events is not None and sent >= max_events:
                break
            now_ns = time.time_ns()
            if not slept:
                # gap across an intentional sleep is nominal; only a gap
                # between BUSY iterations indicates starvation/oversleep
                max_gap_ms = max(max_gap_ms, (now_ns - last_loop_ns) / 1e6)
            last_loop_ns = now_ns
            slept = False
            if duration_s is not None and now_ns - start_ns >= duration_s * 1e9:
                break
            # Events 0..due-1 are due strictly by schedule (floor, no
            # emit-ahead): with a "+1" here at least one event is always
            # due, the sleep branch never runs, and the loop degenerates
            # into ~8 kHz micro-batches whose per-call overhead IS the
            # producer's throughput ceiling (observed: ~160k ev/s).  The
            # floor form emits each event at most one period late and
            # keeps the intended ~tick_s cadence.
            due = min(
                int((now_ns - start_ns) / period_ns),
                max_events if max_events is not None else 1 << 62,
            )
            # Cap one iteration's emission at 1 s of schedule: after a
            # long scheduler stall the backlog must drain in chunks so the
            # duration/SIGTERM checks keep running (an uncapped burst once
            # held a producer 17 s past its deadline inside one emit).
            due = min(due, sent + throughput)
            if due > sent:
                behind_ms = (now_ns - (start_ns + sent * period_ns)) / 1e6
                if behind_ms > 100 and on_behind:
                    on_behind(behind_ms)  # "Falling behind by: N ms"
                t1 = time.time_ns()
                ts = (start_ns + _np.arange(sent, due, dtype=_np.int64)
                      * period_ns) // 1_000_000
                t2 = time.time_ns()
                blob = src.events_blob_view(ts) if blob_ok else None
                t3 = time.time_ns()
                if blob is not None:
                    # zero-copy: the view targets the source's buffer,
                    # consumed fully by this write before the next format
                    sink.append_bytes(blob)
                else:
                    sink.append_many(src.events_at(ts.tolist()))
                t4 = time.time_ns()
                sub_max["ts"] = max(sub_max["ts"], (t2 - t1) / 1e6)
                sub_max["fmt"] = max(sub_max["fmt"], (t3 - t2) / 1e6)
                sub_max["write"] = max(sub_max["write"], (t4 - t3) / 1e6)
                path_now = "native" if blob is not None else "python"
                if path_now != last_path:
                    # Report every path CHANGE, not just the first batch:
                    # a mid-run fallback to the ~60x slower Python
                    # formatter would otherwise be indistinguishable from
                    # an engine problem in the sweep's numbers.
                    last_path = path_now
                    print(f"formatter: {path_now}", flush=True)
                # Make the batch visible to tailing consumers immediately:
                # producer buffering must not pollute end-to-end latency.
                sink.flush()
                sub_max["flush"] = max(sub_max["flush"],
                                       (time.time_ns() - t4) / 1e6)
                max_emit_ms = max(max_emit_ms,
                                  (time.time_ns() - now_ns) / 1e6)
                sent = due
                # NO rest after an emit: at high rates the next event is
                # due within microseconds, and on a contended single core
                # a sleeping producer pays wake latency + unaccounted
                # emit time every tick — a built-in rate deficit that
                # spirals (measured: 225k/s collapsed to ~50k/s).  The
                # hot loop stays cheap because the emit path is
                # zero-copy C; it parks in the branch below whenever the
                # schedule truly has nothing due.
            else:
                time.sleep(tick_s)
                slept = True
    except SystemExit:
        # STOP_LOAD's SIGTERM (stream-bench.sh:231) raised mid-loop: stop
        # cleanly so the caller still reports/flushes the true count.
        pass
    final_behind = (time.time_ns() - (start_ns + sent * period_ns)) / 1e6
    if on_behind is not None and final_behind > 100:
        on_behind(final_behind)
    print(f"pacing: max_emit={max_emit_ms:.0f}ms max_gap={max_gap_ms:.0f}ms "
          + " ".join(f"max_{k}={v:.0f}ms" for k, v in sub_max.items()),
          flush=True)
    sink.flush()
    return sent


def get_stats(r: RedisLike, workdir: str = ".") -> list[tuple[int, int]]:
    """``-g``: collect (seen, latency) to ``seen.txt``/``updated.txt``
    (``get-stats``, ``core.clj:130-149``)."""
    stats = read_stats(r)
    with open(os.path.join(workdir, SEEN_FILE), "w") as f:
        f.write("".join(f"{seen}\n" for seen, _ in stats))
    with open(os.path.join(workdir, UPDATED_FILE), "w") as f:
        f.write("".join(f"{lat}\n" for _, lat in stats))
    return stats


def dostats(workdir: str = ".", time_divisor_ms: int = 10_000,
            events: Iterable[bytes | str] | None = None,
            mapping_path: str | None = None,
            mapping: dict[str, str] | None = None
            ) -> dict[str, dict[int, int]]:
    """The golden model (``dostats``, ``core.clj:101-128``): replay the
    journal in pure Python, count "view" events per (campaign, bucket).

    Returns ``campaign -> {time_bucket -> count}`` with *bucket indices*
    (event_time // divisor), as the Clojure original does.  ``mapping``
    supplies the ad->campaign join directly (tests); else it loads from
    ``mapping_path`` / the workdir file.
    """
    if mapping is None:
        mapping = load_ad_mapping_file(
            mapping_path or os.path.join(workdir, AD_TO_CAMPAIGN_FILE))
    own_file = None
    if events is None:
        own_file = open(os.path.join(workdir, KAFKA_JSON_FILE), "rb")
        events = own_file
    acc: dict[str, dict[int, int]] = {}
    try:
        for line in events:
            if not line.strip():
                continue
            ev = json.loads(line)
            if ev["event_type"] != "view":
                continue
            campaign = mapping.get(ev["ad_id"])
            if campaign is None:
                continue
            bucket = int(ev["event_time"]) // time_divisor_ms
            per = acc.setdefault(campaign, {})
            per[bucket] = per.get(bucket, 0) + 1
    finally:
        if own_file is not None:
            own_file.close()
    return acc


def check_correct(r: RedisLike, workdir: str = ".",
                  time_divisor_ms: int = 10_000,
                  log: Callable[[str], None] = print
                  ) -> tuple[int, int, int]:
    """``-c``: diff the golden model against what the engine wrote to Redis
    (``check-correct``, ``core.clj:215-237``).

    Returns ``(correct, differ, missing)`` window counts; prints per-window
    CORRECT/DIFFER lines like the original.
    """
    expected = dostats(workdir, time_divisor_ms)
    actual = read_seen_counts(r)
    correct = differ = missing = 0
    for campaign, per_bucket in expected.items():
        got = actual.get(campaign, {})
        for bucket, want in per_bucket.items():
            window_ts = bucket * time_divisor_ms
            have = got.get(window_ts)
            if have is None:
                missing += 1
                log(f"Campaign: {campaign!r} has no entry for Timestamp: "
                    f"{window_ts}, was expecting {want}")
            elif have != want:
                differ += 1
                log(f"Campaign: {campaign!r} Timestamp: {window_ts} DIFFER "
                    f"in seen count: ({have}, {want})")
            else:
                correct += 1
    return correct, differ, missing
