"""Sketch-aggregation engines: BASELINE configs #2-#4.

Same host loop, encoder, Redis writer, and harness contract as the exact
count engine (``AdAnalyticsEngine``) — only the device aggregation state
changes, exactly how the reference swaps ``CampaignProcessorCommon`` for a
different processor while keeping the topology (SURVEY.md §7.6).  All
three sketches merge with psum/pmax-shaped reductions, so the sharded
variants come from the same mesh treatment as the exact engine.

- ``HLLDistinctEngine`` — distinct users per (campaign, 10 s window) via
  HyperLogLog registers in place of exact counts.  Estimates are
  *absolute*, so window writebacks HSET rather than HINCRBY.
- ``SlidingTDigestEngine`` — sliding-window (size/slide) view counts plus
  a per-campaign t-digest over event latency; quantiles dump to Redis at
  close.
- ``SessionCMSEngine`` — session windows (gap-based) of per-user clicks,
  feeding a count-min sketch whose top-k heavy hitters dump at close.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.engine.pipeline import AdAnalyticsEngine
from streambench_tpu.io.redis_schema import RedisLike
from streambench_tpu.ops import (cms, hll, hllx, minhash, salsa, session,
                                 sliding, tdigest)
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.utils.ids import now_ms


class _SketchEngineBase(AdAnalyticsEngine):
    """Shared checkpoint plumbing for sketch engines.

    Sketch state is keyed by *interned* user/page indices (HLL register
    hashes, session rows, CMS columns), so every snapshot also carries the
    encoder's intern tables — a resumed encoder must re-assign identical
    indices or restored sketch contents would silently drift (the
    exact-count engine never needed this; its state is keyed by campaign,
    which is fixed up front).  Resume semantics match the base engine:
    at-least-once relative to the journal offset
    (``AdvertisingTopologyNative.java:92`` / ``checkpoint.py``).
    """

    # Sketch kernels have no scanned form yet; process_chunk folds
    # per-batch (deferred drains still apply).
    SCAN_SUPPORTED = False
    # Sketch _device_step implementations always ship separate columns
    # (only their scans have packed forms) — keeps the transfer ledger's
    # per-format accounting honest.
    STEP_PACKS = False
    # Sketch device state is keyed by interned indices: one consistent
    # intern table is mandatory, so no per-thread parallel encoders and
    # interning stays ON.
    PARALLEL_ENCODE_OK = False
    NEEDS_INTERNED_IDS = True

    @staticmethod
    def _pack_keys(keys: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """Concatenated uint8 blob + int64 offsets.  NOT an "S"-dtype
        array: numpy's fixed-width bytes strip trailing NULs, which would
        corrupt ids and collapse distinct keys on restore."""
        blob = b"".join(keys)
        offs = np.zeros(len(keys) + 1, np.int64)
        np.cumsum([len(k) for k in keys], out=offs[1:])
        return np.frombuffer(blob, np.uint8) if blob else \
            np.zeros(0, np.uint8), offs

    @staticmethod
    def _unpack_keys(blob: np.ndarray, offs: np.ndarray) -> list[bytes]:
        raw = blob.tobytes()
        return [raw[offs[i]:offs[i + 1]] for i in range(len(offs) - 1)]

    def _intern_extra(self) -> dict:
        users, pages = self.encoder.dump_intern_tables()
        ub, uo = self._pack_keys(users)
        pb, po = self._pack_keys(pages)
        return {"user_blob": ub, "user_offs": uo,
                "page_blob": pb, "page_offs": po}

    def _restore_interns(self, snap) -> None:
        self.encoder.restore_intern_tables(
            self._unpack_keys(snap.extra["user_blob"],
                              snap.extra["user_offs"]),
            self._unpack_keys(snap.extra["page_blob"],
                              snap.extra["page_offs"]))

    def _now_rel(self) -> jnp.int32:
        """Host clock rebased to the encoder origin, clamped into int32
        (the ONE copy of the two-clock rebase used by every sketch
        engine's latency sampling paths)."""
        base = self.encoder.base_time_ms or 0
        return jnp.int32(np.clip(np.int64(now_ms()) - base, 0, 2**31 - 2))


class HLLDistinctEngine(_SketchEngineBase):
    """Distinct users per (campaign, window): HLL registers on device.

    BASELINE config #2 — 'HyperLogLog distinct-user-per-campaign sketch in
    place of exact count'.  ``seen_count`` in the canonical Redis schema
    becomes the distinct estimate; re-flushes of a still-open window
    replace the previous estimate (absolute semantics).
    """

    absolute_counts = True

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 registers: int = 128,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.registers = registers
        self.state = hll.init_state(self.encoder.num_campaigns, self.W,
                                    num_registers=registers)

    # HLL consumes user identity only through a hash (the kernel
    # splitmix-mixes the column anyway), so the encoder emits stateless
    # crc32 ids: consistent across pool workers and process restarts.
    # That unwinds both sketch-base restrictions — the parallel encode
    # pool is sound again, and snapshots need no intern tables (legacy
    # snapshots with tables still restore; estimates for windows
    # spanning an OLD intern-keyed snapshot may recount users once).
    HASHED_IDS = True
    NEEDS_INTERNED_IDS = False
    PARALLEL_ENCODE_OK = True
    SCAN_SUPPORTED = True
    SCAN_COLUMNS = ("ad_idx", "user_idx", "event_type", "event_time",
                    "valid")

    def _device_step(self, batch) -> None:
        self.state = hll.step(
            self.state, self.join_table,
            jnp.asarray(batch.ad_idx), jnp.asarray(batch.user_idx),
            jnp.asarray(batch.event_type), jnp.asarray(batch.event_time),
            jnp.asarray(batch.valid),
            divisor_ms=self.divisor, lateness_ms=self.lateness)

    def _device_scan(self, ad_idx, user_idx, event_type, event_time,
                     valid) -> None:
        self.state = hll.scan_steps(
            self.state, self.join_table, ad_idx, user_idx, event_type,
            event_time, valid, divisor_ms=self.divisor,
            lateness_ms=self.lateness)

    PACKED_EXTRA_COLS = ("user_idx",)

    def _device_scan_packed(self, packed, user_idx, event_time) -> None:
        self.state = hll.scan_steps_packed(
            self.state, self.join_table, packed, user_idx, event_time,
            divisor_ms=self.divisor, lateness_ms=self.lateness)

    ENGINE_FAMILY = "hll"

    def snapshot(self, offset: int):
        from streambench_tpu.checkpoint import Snapshot

        self._snapshot_sync()
        meta = self._snapshot_meta()
        meta["num_registers"] = self.registers
        return self._xo_decorate(Snapshot(
            offset=offset, meta=meta,
            counts=np.zeros((0, 0), np.int32),  # registers live in extra
            window_ids=np.asarray(self.state.window_ids),
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped),
            pending=[(c, ts, n) for (c, ts), n in self._pending.items()],
            latency=sorted(self.window_latency.items()),
            extra={"hll_registers": np.asarray(self.state.registers),
                   **self._intern_extra()},
        ))

    def restore(self, snap) -> None:
        self._check_geometry(snap, extra={"num_registers": self.registers})
        self._flush_cache = None  # post-restore drains must rewrite all
        self.state = hll.HLLState(
            registers=jnp.asarray(snap.extra["hll_registers"]),
            window_ids=jnp.asarray(snap.window_ids),
            watermark=jnp.int32(snap.watermark),
            dropped=jnp.int32(snap.dropped))
        self._restore_interns(snap)
        self._restore_host(snap)

    def _drain_device(self) -> None:
        """Dispatch-only (parked) estimate drain: the blocking
        ``np.asarray`` pulls this used to do inline cost ~90-150 ms each
        over a tunneled accelerator — and seconds behind a backed-up
        transfer queue; the absorb logic now runs at materialization
        time (``_materialize_custom``)."""
        est, wids, self.state = hll.flush(
            self.state, divisor_ms=self.divisor, lateness_ms=self.lateness)
        self._park(("hll", est, wids))
        # Open windows keep their registers on device, so the unflushed
        # event-time span restarts at the oldest still-open window, not
        # at the next batch (the base engine drains everything and can
        # reset to None).  Computed from the HOST-tracked watermark —
        # pulling window_ids here would block exactly like the pull this
        # parking removes.
        self._span_start = self._oldest_open_span_start()

    def _materialize_custom(self, parked: tuple) -> None:
        tag, est_d, wids_d = parked
        assert tag == "hll", tag
        est = np.asarray(est_d)
        wids = np.asarray(wids_d)
        base = self.encoder.base_time_ms or 0
        # Re-flush only CHANGED estimates: an open window whose registers
        # saw no new user since the last drain must not be re-written —
        # the rewrite would advance its time_updated every second and the
        # canonical latency metric (final time_updated - window_ts,
        # core.clj:149) would read as the window's lifetime in the ring
        # (up to lateness) instead of its writeback latency.
        cache = getattr(self, "_flush_cache", None)
        if cache is None or cache[0].shape != est.shape:
            cache = (np.zeros_like(est), np.full_like(wids, -2))
        prev_est, prev_wids = cache
        fresh_slot = wids != prev_wids               # [W]
        changed = fresh_slot[None, :] | (est != prev_est)
        live = (est > 0) & changed & (wids >= 0)[None, :]
        ci, si = np.nonzero(live)          # vectorized: the per-cell
        if ci.size:                        # Python loop cost ~1 us/cell
            self._pending_np.append(
                (ci.astype(np.int64),
                 base + wids[si].astype(np.int64) * self.divisor,
                 est[ci, si].astype(np.int64)))
        self._flush_cache = (est, wids)

    @property
    def dropped(self) -> int:
        return int(self.state.dropped)


class ReachSketchEngine(_SketchEngineBase):
    """Cumulative per-campaign reach sketches: MinHash signature + HLL
    plane, served live (ISSUE 10 / ROADMAP item 4).

    Unlike every windowed engine, reach state is *cumulative audience*:
    there is no ring, no lateness cutoff, and nothing is ever dropped —
    ``flush()`` writes no canonical window rows (like the session
    engine) and instead pushes the current sketch planes to an attached
    :class:`reach.serve.ReachQueryServer` so concurrent
    union/intersection/overlap queries evaluate against materialized
    state.  ``close()`` additionally writes per-campaign reach
    estimates to ``<redis.hashtable>_reach``.
    """

    ENGINE_FAMILY = "reach"
    # Reach consumes user identity only through hashes (exactly the HLL
    # rationale): stateless crc32 ids, parallel encode pool sound, no
    # intern tables in snapshots.
    HASHED_IDS = True
    NEEDS_INTERNED_IDS = False
    PARALLEL_ENCODE_OK = True
    SCAN_SUPPORTED = True
    SCAN_COLUMNS = ("ad_idx", "user_idx", "event_type", "event_time",
                    "valid")
    PACKED_EXTRA_COLS = ("user_idx",)

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 k: int | None = None, registers: int = 256,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.k = int(k if k is not None else cfg.jax_reach_k)
        self.registers = int(registers)
        self.state = minhash.init_state(self.encoder.num_campaigns,
                                        self.k, self.registers)
        # Cumulative sketches have no ring to overrun: disable the span
        # guard (same rule as the session engine) so catchup chunks
        # never fall back to the per-batch fold for nothing.
        self._span_guard = 2**31 - 1
        # Query-serving attachment (reach/serve.py): None until
        # attach_reach — the fold hot path pays one None check per
        # flush, nothing per batch.
        self._reach_server = None
        # Replica snapshot shipper (reach/replica.py, ISSUE 14): ships
        # (epoch, planes, watermark) records at its cadence from the
        # same flush-path push.
        self._reach_shipper = None
        # Epoch of the served state: bumped on every restore so a
        # post-resume answer is distinguishable from a stale one (the
        # chaos sweep's "never return stale-epoch estimates" check).
        self.reach_epoch = 0
        # Fleet freshness (ISSUE 15): wall stamp of the last fold
        # dispatch into the planes — the fold-anchored end of the
        # freshness ledger.  One now_ms() per dispatch (tens of ns),
        # stamped unconditionally; it only reaches the wire when
        # jax.obs.fleet is on.
        self._fold_wall_ms: int | None = None
        # Dirty-campaign tracking (ISSUE 18): a host-side [C] bool mask
        # unioned per fold from the already-encoded campaign columns —
        # zero device cost, O(batch) host work — consumed by a delta
        # shipper (wants_dirty=True) to gather only the touched rows.
        # None until such a shipper attaches, so non-delta runs pay one
        # None check per fold.  (The PR 9 shard-hist trick is where a
        # device-side dirty-mask variant could ride later.)
        self._dirty_mask: np.ndarray | None = None
        self._join_np: np.ndarray | None = None

    # -- dirty-row tracking (ISSUE 18) ---------------------------------
    def _mark_dirty(self, ad_idx, valid=None) -> None:
        """Union this fold's touched campaigns into the dirty mask.
        Marking a superset (e.g. rows a later predicate zeroes out) is
        always sound — a clean row shipped early is idempotent under
        the min/max merge algebra."""
        m = self._dirty_mask
        if m is None:
            return
        ad = np.asarray(ad_idx).ravel()
        if valid is not None:
            v = np.asarray(valid).ravel().astype(bool)
            if v.size == ad.size:
                ad = ad[v]
        ad = ad[(ad >= 0) & (ad < self._join_np.size)]
        camp = self._join_np[ad]
        camp = camp[(camp >= 0) & (camp < m.size)]
        m[camp] = True

    def _mark_dirty_packed(self, packed) -> None:
        if self._dirty_mask is None:
            return
        from streambench_tpu.ops.windowcount import (
            PACK_AD_BITS,
            PACK_AD_MAX,
        )

        w = np.asarray(packed).ravel().astype(np.int64)
        valid = (w >> (PACK_AD_BITS + 2)) & 1
        self._mark_dirty(w & (PACK_AD_MAX - 1), valid)

    def _device_step(self, batch) -> None:
        self.state = minhash.step(
            self.state, self.join_table,
            jnp.asarray(batch.ad_idx), jnp.asarray(batch.user_idx),
            jnp.asarray(batch.event_type), jnp.asarray(batch.event_time),
            jnp.asarray(batch.valid))
        self._fold_wall_ms = now_ms()
        if self._dirty_mask is not None:
            self._mark_dirty(batch.ad_idx, batch.valid)

    def _device_scan(self, ad_idx, user_idx, event_type, event_time,
                     valid) -> None:
        self.state = minhash.scan_steps(
            self.state, self.join_table, ad_idx, user_idx, event_type,
            event_time, valid)
        self._fold_wall_ms = now_ms()
        if self._dirty_mask is not None:
            self._mark_dirty(ad_idx, valid)

    def _device_scan_packed(self, packed, user_idx, event_time) -> None:
        self.state = minhash.scan_steps_packed(
            self.state, self.join_table, packed, user_idx, event_time)
        self._fold_wall_ms = now_ms()
        if self._dirty_mask is not None:
            self._mark_dirty_packed(packed)

    def warmup(self) -> None:
        """Base warmup + the close-time estimate program:
        ``minhash.estimate`` first runs when ``close()`` writes the
        reach hash, and an uncompiled program there lands AFTER
        ``mark_steady`` — a false mid-run-stall warning from the
        recompile detector.  ``estimate`` is read-only, so compiling
        it here is state-neutral."""
        super().warmup()
        np.asarray(minhash.estimate(self.state.registers))

    # -- serving -------------------------------------------------------
    def query_callable(self):
        """The batch evaluator an attached query server dispatches
        through (the sharded subclass swaps in its shard-local
        two-collective program)."""
        from streambench_tpu.reach import query as rq

        return rq.batch_query

    def attach_reach(self, server) -> None:
        """Wire a ReachQueryServer: inject this engine's evaluator,
        immediate initial push (possibly empty state — queries answer 0
        until events fold), then a fresh push on every flush and on
        restore."""
        self._reach_server = server
        use = getattr(server, "use_query_fn", None)
        if use is not None:
            use(self.query_callable())
        self._reach_push()

    def attach_shipper(self, shipper) -> None:
        """Wire a replica SnapshotShipper: ships from the same
        flush-cadence push path the query server rides (the writer is
        never blocked by readers — a ship is one host gather + one
        appended log line, and only at the shipping cadence).

        The attach itself FORCES a ship: a supervisor-restarted writer
        re-attaches mid-lineage, and without the forced ship a replica
        behind the crash would keep serving the pre-crash record until
        the next cadence tick (the ISSUE 15 restart-path fix — the
        close-time forced ship's twin)."""
        self._reach_shipper = shipper
        if getattr(shipper, "wants_dirty", False):
            # delta shipping (ISSUE 18): host-side dirty-campaign mask
            # + a host copy of the join table (ad -> campaign) so the
            # per-fold union never touches the device
            self._dirty_mask = np.zeros(self.encoder.num_campaigns,
                                        dtype=bool)
            self._join_np = np.asarray(self.join_table)
        self._reach_push(force_ship=True)

    def planes(self) -> dict:
        """The plane-generic shipping surface (ISSUE 18 / ROADMAP item
        2): named state planes whose rows merge elementwise — what a
        DeltaShipper's ``note_planes`` consumes."""
        return {"mins": self.state.mins,
                "registers": self.state.registers}

    def _reach_push(self, force_ship: bool = False) -> None:
        if self._reach_server is not None:
            self._reach_server.update_state(
                self.state.mins, self.state.registers, self.reach_epoch,
                freshness=self._fleet_stamps())
        sh = self._reach_shipper
        if sh is not None and (force_ship or sh.due(self.reach_epoch)):
            # the due() pre-check keeps the watermark pull (a device
            # sync) off the not-yet-due flushes
            dirty = (np.flatnonzero(self._dirty_mask)
                     if self._dirty_mask is not None else None)
            shipped = sh.note_state(
                self.state.mins, self.state.registers,
                self.reach_epoch, int(self.state.watermark),
                force=force_ship, folded_ms=self._fold_wall_ms,
                dirty_rows=dirty)
            if shipped and self._dirty_mask is not None:
                # rows shipped (in a delta or covered by a base) are
                # clean until the next fold touches them
                self._dirty_mask[:] = False

    def _fleet_stamps(self) -> dict | None:
        """Writer-attached freshness stamps (``jax.obs.fleet``): the
        server answers against live planes, so submit/ship/load all
        collapse to the push stamp — only ``fold_lag`` (push minus last
        fold) and ``serve`` (reply minus push) have width.  None when
        fleet obs is off, keeping replies byte-identical."""
        if not getattr(self.cfg, "jax_obs_fleet", False):
            return None
        push = now_ms()
        return {"folded_ms": self._fold_wall_ms or push,
                "submit_ms": push, "shipped_ms": push,
                "loaded_ms": push}

    # -- harness hooks -------------------------------------------------
    def _drain_device(self) -> None:
        # nothing to drain: sketches are cumulative, estimates are read
        # (not reset) at flush/close
        self._span_start = None

    def flush(self, time_updated: int | None = None, *,
              final: bool = False) -> int:
        self._reach_push()
        return 0   # reach has no canonical window rows

    def estimates(self) -> np.ndarray:
        """Per-campaign distinct-device estimates ``[C]`` (HLL plane)."""
        return np.asarray(minhash.estimate(self.state.registers))

    def snapshot(self, offset: int):
        from streambench_tpu.checkpoint import Snapshot

        self._snapshot_sync()
        meta = self._snapshot_meta()
        meta.update(reach_k=self.k, num_registers=self.registers,
                    reach_epoch=self.reach_epoch)
        return self._xo_decorate(Snapshot(
            offset=offset, meta=meta,
            counts=np.zeros((0, 0), np.int32),
            window_ids=np.zeros((0,), np.int32),  # no window ring
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped),
            extra={"mh_mins": np.asarray(self.state.mins),
                   "hll_plane": np.asarray(self.state.registers),
                   **self._intern_extra()},
        ))

    def restore(self, snap) -> None:
        self._check_geometry(snap, extra=dict(
            reach_k=self.k, num_registers=self.registers))
        self.state = minhash.ReachState(
            mins=jnp.asarray(snap.extra["mh_mins"]),
            registers=jnp.asarray(snap.extra["hll_plane"]),
            watermark=jnp.int32(snap.watermark),
            dropped=jnp.int32(snap.dropped))
        self._restore_interns(snap)
        self._restore_host(snap)
        # Every restore begins a new serving epoch STRICTLY ABOVE both
        # the snapshot's and the current lineage's — answers computed
        # against pre-crash state are then detectable by epoch alone.
        self.reach_epoch = max(self.reach_epoch,
                               int(snap.meta.get("reach_epoch", 0))) + 1
        # restart-path forced ship (ISSUE 15): the post-restore planes
        # must reach the replica log NOW, not at the next cadence tick
        # — a replica behind a crashed writer otherwise keeps serving
        # the pre-crash epoch for up to one full shipping interval
        self._reach_push(force_ship=True)

    def close(self) -> None:
        self._reach_push()
        if self.redis is not None and self.cfg.redis_hashtable:
            est = self.estimates()
            table = f"{self.cfg.redis_hashtable}_reach"
            cmds = [("HSET", table, name, str(int(round(float(e)))))
                    for name, e in zip(self.encoder.campaigns, est)
                    if e > 0]
            if cmds:
                self.redis.pipeline_execute(cmds)

    @property
    def dropped(self) -> int:
        return int(self.state.dropped)


class HLLXEngine(_SketchEngineBase):
    """Distinct count AND frequency moments from one register plane:
    the hyper-extended HLL ladder (``ops/hllx.py``, ``--engine hllx``,
    ISSUE 13 / ROADMAP item 2).

    Cumulative per campaign like the reach engine — no window ring,
    nothing ever drops; ``flush()`` writes no canonical rows and
    ``close()`` writes ``<redis.hashtable>_hllx`` fields per campaign:
    ``<name>:distinct`` (rung-0 HLL), ``<name>:logm`` (the calibrated
    log-count moment), ``<name>:views`` (exact F1), and
    ``<name>:cap<T>`` soft-capped counts for each ladder rung.  All of
    it from a single scatter-max per batch — zero ingest cost over the
    plain distinct engine beyond the G-fold register axis.
    """

    ENGINE_FAMILY = "hllx"
    # Identity is consumed through hashes only — same rationale as the
    # HLL/reach engines: stateless crc32 ids, parallel encode pool
    # sound, no intern tables in snapshots.
    HASHED_IDS = True
    NEEDS_INTERNED_IDS = False
    PARALLEL_ENCODE_OK = True
    SCAN_SUPPORTED = True
    SCAN_COLUMNS = ("ad_idx", "user_idx", "event_type", "event_time",
                    "valid")
    PACKED_EXTRA_COLS = ("user_idx",)

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 groups: int = 8, registers: int = 128,
                 input_format: str = "json"):
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.groups = int(groups)
        self.registers = int(registers)
        self.state = hllx.init_state(self.encoder.num_campaigns,
                                     self.groups, self.registers)
        # cumulative state has no ring to overrun: disable the span
        # guard (the session/reach rule) so catchup chunks never fall
        # back to the per-batch fold for nothing
        self._span_guard = 2**31 - 1

    def _device_step(self, batch) -> None:
        self.state = hllx.step(
            self.state, self.join_table,
            jnp.asarray(batch.ad_idx), jnp.asarray(batch.user_idx),
            jnp.asarray(batch.event_type), jnp.asarray(batch.event_time),
            jnp.asarray(batch.valid))

    def _device_scan(self, ad_idx, user_idx, event_type, event_time,
                     valid) -> None:
        self.state = hllx.scan_steps(
            self.state, self.join_table, ad_idx, user_idx, event_type,
            event_time, valid)

    def _device_scan_packed(self, packed, user_idx, event_time) -> None:
        self.state = hllx.scan_steps_packed(
            self.state, self.join_table, packed, user_idx, event_time)

    def warmup(self) -> None:
        """Base warmup + the close-time moments program (the reach-
        engine rule: a read-only estimator compiling after
        ``mark_steady`` reads as a mid-run stall)."""
        super().warmup()
        jax.block_until_ready(hllx.moments(self.state)["distinct"])

    # -- harness hooks -------------------------------------------------
    def _drain_device(self) -> None:
        self._span_start = None   # cumulative: nothing to drain

    def flush(self, time_updated: int | None = None, *,
              final: bool = False) -> int:
        return 0   # no canonical window rows

    def moments(self) -> dict:
        """Host copies of every ladder answer ([C] / [C, G] arrays)."""
        return {k: np.asarray(v)
                for k, v in hllx.moments(self.state).items()}

    def snapshot(self, offset: int):
        from streambench_tpu.checkpoint import Snapshot

        self._snapshot_sync()
        meta = self._snapshot_meta()
        meta.update(hllx_groups=self.groups,
                    num_registers=self.registers)
        return self._xo_decorate(Snapshot(
            offset=offset, meta=meta,
            counts=np.zeros((0, 0), np.int32),
            window_ids=np.zeros((0,), np.int32),   # no window ring
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped),
            extra={"hllx_registers": np.asarray(self.state.registers),
                   "hllx_totals": np.asarray(self.state.totals),
                   **self._intern_extra()},
        ))

    def restore(self, snap) -> None:
        self._check_geometry(snap, extra=dict(
            hllx_groups=self.groups, num_registers=self.registers))
        self.state = hllx.HLLXState(
            registers=jnp.asarray(snap.extra["hllx_registers"]),
            totals=jnp.asarray(snap.extra["hllx_totals"]),
            watermark=jnp.int32(snap.watermark),
            dropped=jnp.int32(snap.dropped))
        self._restore_interns(snap)
        self._restore_host(snap)

    def close(self) -> None:
        if self.redis is None or not self.cfg.redis_hashtable:
            return
        m = self.moments()
        table = f"{self.cfg.redis_hashtable}_hllx"
        caps = [1 << g for g in range(self.groups)]
        cmds = []
        for c, name in enumerate(self.encoder.campaigns):
            if m["totals"][c] <= 0:
                continue
            cmds.append(("HSET", table, f"{name}:distinct",
                         str(int(round(float(m["distinct"][c]))))))
            cmds.append(("HSET", table, f"{name}:logm",
                         f"{float(m['log_moment'][c]):.1f}"))
            cmds.append(("HSET", table, f"{name}:views",
                         str(int(m["totals"][c]))))
            for g, t in enumerate(caps):
                cmds.append(("HSET", table, f"{name}:cap{t}",
                             f"{float(m['softcap'][c, g]):.1f}"))
        if cmds:
            self.redis.pipeline_execute(cmds)

    @property
    def dropped(self) -> int:
        return int(self.state.dropped)


def _cms_auto(backend: str, width: int) -> str:
    """Resolve ``jax.cms.mode=auto``: the SALSA plane where the
    measured cms-family winner (``ops.methodbench``, keyed
    backend/cms/W<Wd>) says its update is the fastest arm; fixed
    otherwise — auto picks by SPEED, memory-motivated deployments set
    mode=salsa explicitly (the memory win is unconditional, the update
    cost is the backend-dependent part)."""
    try:
        from streambench_tpu.ops import methodbench

        winner = methodbench.cms_winner(backend, width)
    except Exception:
        winner = None
    return "salsa" if winner == "salsa" else "fixed"


def _sliced_auto(backend: str, S: int, C: int, W: int) -> bool:
    """Resolve ``jax.sliding.sliced=auto``: the sliced fold wherever
    its [C, S, W] class plane fits and the measured sliding-family
    winner (``ops.methodbench``, cached per backend/S-bucket) does not
    say otherwise.  Unmeasured geometries default ON — one claim + one
    scatter beats S claims + S scatters on every backend measured so
    far, and the bit-identity sweep pins correctness either way."""
    if S > W or C * S * W > (1 << 27):
        return False
    try:
        from streambench_tpu.ops import methodbench

        winner = methodbench.sliding_winner(backend, S)
    except Exception:
        winner = None
    return winner is None or winner == "sliced"


@functools.partial(jax.jit, static_argnames=("size_ms", "slide_ms",
                                             "lateness_ms", "method"))
def _sliding_tdigest_scan(win_state, digest, join_table, now_rel,
                          ad_idx, event_type, event_time, valid,
                          *, size_ms: int, slide_ms: int,
                          lateness_ms: int, method: str = "scatter"):
    """Fused sliding-window + t-digest scan over ``[N, B]`` batches.

    One dispatch per chunk, digest samples taken against a single
    ``now_rel`` stamp captured at dispatch time (the same two-clock
    semantics as the per-batch path, which also reads the host clock
    once per Python-level step).  Latency samples accumulate in the
    value-bucketed histogram across the whole chunk and compress into
    the digest ONCE at the end — the scan body is pure O(B) scatters
    (the per-batch compress was most of config #3's device time)."""
    N = digest.means.shape[0]

    def body(carry, xs):
        st, hn, hw = carry
        a, et, t, v = xs
        st = sliding.step(st, join_table, a, et, t, v, size_ms=size_ms,
                          slide_ms=slide_ms, lateness_ms=lateness_ms,
                          method=method)
        lat = jnp.maximum(now_rel - t, 0)
        campaign = join_table[a]
        mask = v & (et == 0) & (campaign >= 0)
        w = jnp.where(mask, 1.0, 0.0).astype(jnp.float32)
        # fold_hist masks out-of-range keys itself; campaign goes in raw
        hn, hw = tdigest.fold_hist(hn, hw, campaign, lat, w, N)
        return (st, hn, hw), None

    (st, hn, hw), _ = jax.lax.scan(
        body, (win_state,) + tdigest.hist_init(N),
        (ad_idx, event_type, event_time, valid))
    return st, tdigest.absorb_hist(digest, hn, hw)


@functools.partial(jax.jit, static_argnames=("size_ms", "slide_ms",
                                             "lateness_ms", "method"))
def _sliding_tdigest_scan_packed(win_state, digest, join_table, now_rel,
                                 packed, event_time,
                                 *, size_ms: int, slide_ms: int,
                                 lateness_ms: int,
                                 method: str = "scatter"):
    """``_sliding_tdigest_scan`` over the packed wire word
    (``windowcount.pack_columns``): 8 B/event on the wire instead of
    13 B across four buffers; unpacked per scan step, bit-identical."""
    N = digest.means.shape[0]

    def body(carry, xs):
        st, hn, hw = carry
        p, t = xs
        a, et, v = wc.unpack_columns(p)
        st = sliding.step(st, join_table, a, et, t, v, size_ms=size_ms,
                          slide_ms=slide_ms, lateness_ms=lateness_ms,
                          method=method)
        lat = jnp.maximum(now_rel - t, 0)
        campaign = join_table[a]
        mask = v & (et == 0) & (campaign >= 0)
        w = jnp.where(mask, 1.0, 0.0).astype(jnp.float32)
        hn, hw = tdigest.fold_hist(hn, hw, campaign, lat, w, N)
        return (st, hn, hw), None

    (st, hn, hw), _ = jax.lax.scan(
        body, (win_state,) + tdigest.hist_init(N),
        (packed, event_time))
    return st, tdigest.absorb_hist(digest, hn, hw)


@functools.partial(jax.jit, static_argnames=("size_ms", "slide_ms",
                                             "lateness_ms", "sliced",
                                             "method"))
def _sliding_tdigest_step(win_state, digest, join_table, now_rel,
                          ad_idx, event_type, event_time, valid,
                          *, size_ms: int, slide_ms: int,
                          lateness_ms: int, sliced: bool,
                          method: str = "scatter"):
    """ONE compiled program for the per-batch fold + latency sample.

    The un-fused form (separate ``sliding.step`` dispatch + eager
    ``jnp.maximum``/mask arithmetic + ``tdigest.update`` dispatch) paid
    several op-by-op dispatches per partial batch — measured ~1 s of a
    2M-event catchup on the 1-core host, most of it dispatch overhead,
    not compute (ISSUE 12)."""
    step = sliding.step_sliced_core if sliced else sliding.step
    st = step(win_state, join_table, ad_idx, event_type, event_time,
              valid, size_ms=size_ms, slide_ms=slide_ms,
              lateness_ms=lateness_ms, method=method)
    lat = jnp.maximum(now_rel - event_time, 0)
    campaign = join_table[ad_idx]
    mask = valid & (event_type == 0) & (campaign >= 0)
    dg = tdigest.update(digest, campaign, lat, mask)
    return st, dg


@functools.partial(jax.jit, static_argnames=("size_ms", "slide_ms",
                                             "lateness_ms", "method"))
def _sliding_tdigest_scan_sliced(win_state, digest, join_table, now_rel,
                                 ad_idx, event_type, event_time, valid,
                                 *, size_ms: int, slide_ms: int,
                                 lateness_ms: int,
                                 method: str = "scatter"):
    """``_sliding_tdigest_scan`` over the SLICED fold (ISSUE 12): the
    scan body pays one ring claim + one bucket scatter per batch
    instead of S claim passes; the t-digest half is unchanged."""
    N = digest.means.shape[0]

    def body(carry, xs):
        st, hn, hw = carry
        a, et, t, v = xs
        st = sliding.step_sliced_core(
            st, join_table, a, et, t, v, size_ms=size_ms,
            slide_ms=slide_ms, lateness_ms=lateness_ms, method=method)
        lat = jnp.maximum(now_rel - t, 0)
        campaign = join_table[a]
        mask = v & (et == 0) & (campaign >= 0)
        w = jnp.where(mask, 1.0, 0.0).astype(jnp.float32)
        hn, hw = tdigest.fold_hist(hn, hw, campaign, lat, w, N)
        return (st, hn, hw), None

    (st, hn, hw), _ = jax.lax.scan(
        body, (win_state,) + tdigest.hist_init(N),
        (ad_idx, event_type, event_time, valid))
    return st, tdigest.absorb_hist(digest, hn, hw)


@functools.partial(jax.jit, static_argnames=("size_ms", "slide_ms",
                                             "lateness_ms", "method"))
def _sliding_tdigest_scan_sliced_packed(win_state, digest, join_table,
                                        now_rel, packed, event_time,
                                        *, size_ms: int, slide_ms: int,
                                        lateness_ms: int,
                                        method: str = "scatter"):
    """Sliced fold over the packed wire word (8 B/event)."""
    N = digest.means.shape[0]

    def body(carry, xs):
        st, hn, hw = carry
        p, t = xs
        a, et, v = wc.unpack_columns(p)
        st = sliding.step_sliced_core(
            st, join_table, a, et, t, v, size_ms=size_ms,
            slide_ms=slide_ms, lateness_ms=lateness_ms, method=method)
        lat = jnp.maximum(now_rel - t, 0)
        campaign = join_table[a]
        mask = v & (et == 0) & (campaign >= 0)
        w = jnp.where(mask, 1.0, 0.0).astype(jnp.float32)
        hn, hw = tdigest.fold_hist(hn, hw, campaign, lat, w, N)
        return (st, hn, hw), None

    (st, hn, hw), _ = jax.lax.scan(
        body, (win_state,) + tdigest.hist_init(N),
        (packed, event_time))
    return st, tdigest.absorb_hist(digest, hn, hw)


class SlidingTDigestEngine(_SketchEngineBase):
    """Sliding-window view counts + per-campaign latency t-digest.

    BASELINE config #3 — 'sliding-window (10s / 1s slide) + t-digest
    latency-quantile sketch per campaign'.  Window rows use the canonical
    schema with ``window_ts`` = the slide-aligned window START; counts are
    deltas (HINCRBY) like the exact engine.  At close, per-campaign
    latency quantiles land in the Redis hash
    ``<redis.hashtable>_quantiles`` as ``<campaign>:p<q>`` fields.
    """

    QUANTILES = (0.5, 0.9, 0.99)

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 size_ms: int | None = None, slide_ms: int = 1_000,
                 window_slots: int | None = None,
                 compression: int = 64,
                 sliced: str | None = None,
                 input_format: str = "json"):
        size = size_ms if size_ms is not None else cfg.jax_time_divisor_ms
        late_eff = sliding.effective_lateness(size, slide_ms,
                                              cfg.jax_allowed_lateness_ms)
        # Ring sizing: the floor is lateness + size in SLIDE units, but a
        # floor-sized ring spans so little event time (~28 s at the
        # 10s/1s defaults) that every catchup batch outspans it — the
        # fold path then halves batches and drains per sub-batch, an
        # order-of-magnitude slowdown (measured 18k vs 290k ev/s).  So
        # default W generously while keeping C x W bounded (~2^27 cells).
        # The 2048 floor matters at default scale: a 16-batch catchup
        # chunk spans ~1310 s of event time, and a 1024-slot ring's
        # span guard (~953 s at 1 s slides) forced EVERY chunk down the
        # per-batch sort-based fold — the fused histogram scan never
        # ran (measured 219k vs 1.0M+ ev/s on the v5e chip).
        n_campaigns = len(campaigns) if campaigns else \
            len(set(ad_to_campaign.values()))
        W = window_slots or max(
            late_eff // slide_ms + 3 * (size // slide_ms),
            min(2048, (1 << 27) // max(n_campaigns, 1)))
        cfg2 = dataclasses.replace(
            cfg, jax_window_slots=W, jax_time_divisor_ms=slide_ms,
            jax_allowed_lateness_ms=late_eff)
        super().__init__(cfg2, ad_to_campaign, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.size_ms = size
        self.slide_ms = slide_ms
        self.base_lateness = cfg.jax_allowed_lateness_ms
        # Sliced fold (ISSUE 12; jax.sliding.sliced off/on/auto): the
        # [C, S, W] bucket-plane state replaces the [C, W] window ring;
        # flushed rows are bit-identical, the per-batch device work is
        # one claim + one scatter instead of S of each.
        mode = (sliced if sliced is not None
                else getattr(cfg, "jax_sliding_sliced", "auto"))
        mode = str(mode).strip().lower()
        if mode not in ("off", "on", "auto"):
            raise ValueError(f"sliced must be off/on/auto: {mode!r}")
        S = size // slide_ms
        if mode == "auto":
            self.sliced = _sliced_auto(jax.default_backend(), S,
                                       self.encoder.num_campaigns, self.W)
        else:
            self.sliced = mode == "on"
        if self.sliced:
            self.state = sliding.init_sliced(self.encoder.num_campaigns,
                                             self.W, S)
        self.digest = tdigest.init_state(self.encoder.num_campaigns,
                                         compression=compression)
        # The fused scan carries a [C, HIST_BINS] x2 float32 histogram
        # (8 KB/campaign) across the chunk; past ~16k campaigns that
        # transient dwarfs the digest state, so fall back to the
        # per-batch path (sort-based _fold, O(C*K) memory) there.
        if (self.encoder.num_campaigns * tdigest.HIST_BINS) > (1 << 24):
            self.SCAN_SUPPORTED = False

    ENGINE_FAMILY = "sliding_tdigest"
    SCAN_SUPPORTED = True  # fused sliding+digest scan (columns: default)
    # Sliding counts + latency digests never read user/page columns, so
    # interning is skipped AND per-thread parallel encoders are safe
    # (the sketch-base restriction is about intern consistency, which
    # this engine doesn't depend on).
    NEEDS_INTERNED_IDS = False
    PARALLEL_ENCODE_OK = True

    def _device_scan(self, ad_idx, event_type, event_time, valid) -> None:
        fn = (_sliding_tdigest_scan_sliced if self.sliced
              else _sliding_tdigest_scan)
        self.state, self.digest = fn(
            self.state, self.digest, self.join_table, self._now_rel(),
            ad_idx, event_type, event_time, valid,
            size_ms=self.size_ms, slide_ms=self.slide_ms,
            lateness_ms=self.base_lateness, method=self.method)

    def _device_scan_packed(self, packed, event_time) -> None:
        fn = (_sliding_tdigest_scan_sliced_packed if self.sliced
              else _sliding_tdigest_scan_packed)
        self.state, self.digest = fn(
            self.state, self.digest, self.join_table, self._now_rel(),
            packed, event_time,
            size_ms=self.size_ms, slide_ms=self.slide_ms,
            lateness_ms=self.base_lateness, method=self.method)

    # -- sliced drain + host bookkeeping -------------------------------
    def _track_dirty_rows(self) -> bool:
        # the sliced drain reconstructs windows from the whole bucket
        # plane; per-row gathers don't apply to it
        return False if self.sliced else super()._track_dirty_rows()

    def _drain_device(self) -> None:
        if not self.sliced:
            return super()._drain_device()
        # window deltas reconstructed on device (flush_deltas contract),
        # parked for the SHARED host materialization path
        deltas, wids, self.state = sliding.flush_sliced(
            self.state, size_ms=self.size_ms, slide_ms=self.slide_ms,
            lateness_ms=self.base_lateness)
        self._park(("dense", deltas, wids))
        self._span_start = None

    def snapshot(self, offset: int):
        from streambench_tpu.checkpoint import Snapshot

        self._snapshot_sync()
        meta = self._snapshot_meta()
        meta.update(size_ms=self.size_ms, slide_ms=self.slide_ms,
                    compression=int(self.digest.means.shape[1]),
                    sliced=int(self.sliced))
        # sliced state rides the counts slot as the flattened
        # [C, S*W] bucket plane (Snapshot.counts stays 2-D); restore
        # reshapes it back — geometry is pinned by size/slide/W below
        counts = np.asarray(self.state.counts)
        if self.sliced:
            counts = counts.reshape(counts.shape[0], -1)
        return self._xo_decorate(Snapshot(
            offset=offset, meta=meta,
            counts=counts,
            window_ids=np.asarray(self.state.window_ids),
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped),
            pending=[(c, ts, n) for (c, ts), n in self._pending.items()],
            latency=sorted(self.window_latency.items()),
            extra={"td_means": np.asarray(self.digest.means),
                   "td_weights": np.asarray(self.digest.weights),
                   **self._intern_extra()},
        ))

    def restore(self, snap) -> None:
        self._check_geometry(snap, extra=dict(
            size_ms=self.size_ms, slide_ms=self.slide_ms,
            compression=int(self.digest.means.shape[1]),
            sliced=int(self.sliced)))
        self.state = self._put_state(
            snap.counts, snap.window_ids, snap.watermark, snap.dropped)
        self.digest = tdigest.TDigestState(
            means=jnp.asarray(snap.extra["td_means"]),
            weights=jnp.asarray(snap.extra["td_weights"]))
        self._restore_interns(snap)
        self._restore_host(snap)

    def _put_state(self, counts, window_ids, watermark, dropped):
        if not self.sliced:
            return super()._put_state(counts, window_ids, watermark,
                                      dropped)
        S = self.size_ms // self.slide_ms
        plane = np.asarray(counts).reshape(-1, S, self.W)
        return sliding.SlicedWindowState(
            counts=jnp.asarray(plane),
            window_ids=jnp.asarray(window_ids),
            watermark=jnp.int32(watermark), dropped=jnp.int32(dropped))

    def _device_step(self, batch) -> None:
        # Fold + latency sample in ONE fused program (see
        # _sliding_tdigest_step).  Latency is bucketed per campaign.
        # TWO-CLOCK CAVEAT (SURVEY.md §7 "faithful latency semantics"):
        # now_ms() is THIS host's clock, event_time the generator's; the
        # difference is only meaningful when both run on one node or are
        # NTP-disciplined — exactly the reference's assumption
        # (core.clj:149 subtracts generator stamps from engine-side
        # update times the same way).  Cross-host skew shifts the whole
        # digest by the offset; the _now_rel clamp only stops negative
        # skew from corrupting the digest with negative "latencies".
        self.state, self.digest = _sliding_tdigest_step(
            self.state, self.digest, self.join_table, self._now_rel(),
            jnp.asarray(batch.ad_idx), jnp.asarray(batch.event_type),
            jnp.asarray(batch.event_time), jnp.asarray(batch.valid),
            size_ms=self.size_ms, slide_ms=self.slide_ms,
            lateness_ms=self.base_lateness, sliced=self.sliced,
            method=self.method)

    def quantiles(self) -> np.ndarray:
        """Per-campaign latency quantiles ``[C, len(QUANTILES)]`` (ms)."""
        return np.asarray(tdigest.quantile(
            self.digest, jnp.asarray(self.QUANTILES, jnp.float32)))

    def close(self) -> None:
        super().close()
        if self.redis is not None and self.cfg.redis_hashtable:
            q = self.quantiles()
            cmds = []
            table = f"{self.cfg.redis_hashtable}_quantiles"
            for c, name in enumerate(self.encoder.campaigns):
                for j, qq in enumerate(self.QUANTILES):
                    cmds.append(("HSET", table, f"{name}:p{int(qq * 100)}",
                                 f"{q[c, j]:.1f}"))
            self.redis.pipeline_execute(cmds)


# Session close->absorb latency histogram: 250 ms bins to 120 s + one
# overflow bin.  A histogram (not per-session stamps) keeps the hot path
# free of host syncs; quantiles read from it at report time.
LAT_BIN_MS = 250
LAT_BINS = 481


def _hist_scalar(hist, lat, valid):
    """All rows share one latency (their closure was determined by this
    batch's arrival): one clipped bucket, one add."""
    b = jnp.clip(lat // LAT_BIN_MS, 0, LAT_BINS - 1)
    return hist.at[b].add(jnp.sum(valid.astype(jnp.int32)))


def _hist_rows(hist, lat, valid):
    """Per-row latencies (time-expired closures): masked scatter-add."""
    b = jnp.where(valid, jnp.clip(lat // LAT_BIN_MS, 0, LAT_BINS - 1),
                  LAT_BINS)
    return hist.at[b].add(1, mode="drop")


@functools.partial(jax.jit, static_argnames=("gap_ms", "lateness_ms"))
def _session_cms_scan(sess_state, cms_state, topk_state, closed_n,
                      clicks_n, lat_hist, now_rel, salt,
                      user_idx, event_type, event_time, valid,
                      *, gap_ms: int, lateness_ms: int):
    """Fused session + CMS + heavy-hitter scan over ``[N, B]`` batches.

    The whole config-#4 pipeline — session windowing, CMS fold of closed
    sessions, candidate maintenance, counters, close-latency histogram —
    stays device-resident for a chunk: one dispatch, zero host syncs
    (the per-batch path used to pull closed-session masks to the host
    every step).  Heavy-hitter candidates fold into a chunk-local
    hash-slotted table (O(B) per batch) and merge into the exact ring
    ONCE after the scan — the per-batch ``update_topk`` sort was 80% of
    the chunk's device time.  ``salt`` must differ chunk to chunk so a
    hash collision never shadows the same key pair twice: the engine
    passes a per-chunk sequence number (a wall-clock salt would repeat
    when async dispatch issues several chunks in one millisecond).
    """

    def absorb(cm, ck_acc, closed):
        # family-dispatching update/query (ISSUE 13): the fixed path
        # lowers to the exact pre-existing program; salsa/two-stage
        # trace their own variants off the state's pytree type
        cm = cms.sk_update(cm, closed.user, closed.clicks, closed.valid)
        cn = jnp.sum(closed.valid.astype(jnp.int32))
        ck = jnp.sum(jnp.where(closed.valid, closed.clicks, 0))
        return cm, (ck_acc[0] + cn, ck_acc[1] + ck)

    def body(carry, xs):
        st, cm, ck_acc, hist, ckeys, cests = carry
        u, et, t, v = xs
        st, in_batch, carried = session.step(
            st, u, et, t, v, gap_ms=gap_ms, lateness_ms=lateness_ms)
        # closures determined by THIS batch's evidence: latency = host
        # stamp at dispatch minus the batch's newest event time
        det_lat = jnp.maximum(now_rel - jnp.max(jnp.where(v, t, wc.NEG)),
                              0)
        for closed in (in_batch, carried):
            cm, ck_acc = absorb(cm, ck_acc, closed)
            hist = _hist_scalar(hist, det_lat, closed.valid)
            ckeys, cests = cms.fold_candidates(
                ckeys, cests, closed.user,
                cms.point_query(cm, closed.user), closed.valid, salt)
        return (st, cm, ck_acc, hist, ckeys, cests), None

    M2 = 1 << (4 * topk_state.keys.shape[0] - 1).bit_length()
    (st, cm, (cn, ck), hist, ckeys, _), _ = jax.lax.scan(
        body,
        (sess_state, cms_state, (closed_n, clicks_n), lat_hist)
        + cms.init_candidates(M2),
        (user_idx, event_type, event_time, valid))
    tk = cms.update_topk(cm, topk_state, ckeys, ckeys >= 0)
    return st, cm, tk, cn, ck, hist


class SessionCMSEngine(_SketchEngineBase):
    """Per-user session click aggregation + count-min heavy hitters.

    BASELINE config #4 — 'session-window per-user click aggregation
    (gap=30s) with count-min heavy-hitter sketch'.  Closed sessions (in
    batch, carried, or expired by watermark) feed the CMS keyed by user
    with the session's click count as weight; ``close()`` writes top-k
    user estimates to ``<redis.hashtable>_hh``.
    """

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 gap_ms: int = 30_000, user_capacity: int = 1 << 16,
                 cms_depth: int = 4, cms_width: int = 2048,
                 top_k: int = 16, candidate_capacity: int | None = None,
                 cms_mode: str | None = None,
                 cms_stages: int | None = None,
                 cms_cell_bits: int | None = None,
                 input_format: str = "json"):
        # The heavy-hitter report needs user-id NAMES: the native
        # encoder serves them through its intern-table dump
        # (``NativeEncoder.user_key``), so the C scan path — and with it
        # block ingest — stays available to the session engine.
        super().__init__(cfg, ad_to_campaign, campaigns=campaigns,
                         redis=redis, input_format=input_format)
        self.gap_ms = gap_ms
        self.user_capacity = user_capacity
        self.top_k = top_k
        self.state = session.init_state(user_capacity)
        # Sketch family (ISSUE 13; jax.cms.{mode,cell.bits,stages}):
        # "fixed" keeps the int32 plane byte-identical; "salsa" swaps
        # in the merge-on-overflow uint8 plane; stages=2 adds the
        # SF-style small query stage.  "auto" follows the measured
        # cms-family methodbench winner where one exists.
        mode = str(cms_mode if cms_mode is not None
                   else getattr(cfg, "jax_cms_mode", "fixed")
                   ).strip().lower()
        if mode not in ("fixed", "salsa", "auto"):
            raise ValueError(f"cms_mode must be fixed/salsa/auto: {mode!r}")
        stages = int(cms_stages if cms_stages is not None
                     else getattr(cfg, "jax_cms_stages", 1))
        bits = int(cms_cell_bits if cms_cell_bits is not None
                   else getattr(cfg, "jax_cms_cell_bits", 8))
        if mode == "auto":
            mode = _cms_auto(jax.default_backend(), cms_width)
        if mode == "salsa" and stages == 2:
            raise ValueError(
                "jax.cms.mode=salsa does not compose with "
                "jax.cms.stages=2: the SF small stage refreshes from "
                "fat-stage estimates, pick one counter design")
        self.cms_mode = mode
        self.cms_stages = stages
        self.cms_cell_bits = bits
        if mode == "salsa":
            self.cms = salsa.init_state(depth=cms_depth, width=cms_width,
                                        cell_bits=bits)
        elif stages == 2:
            self.cms = cms.init_two_stage(depth=cms_depth,
                                          width=cms_width)
        else:
            self.cms = cms.init_state(depth=cms_depth, width=cms_width)
        # Device-side heavy-hitter candidate ring: report cost is O(ring),
        # NOT O(interned users) — at config #4 scale (1e5+ users) a
        # full-universe query per report defeats the sketch's
        # sublinearity.
        self.topk = cms.init_topk(candidate_capacity or max(8 * top_k, 128))
        self.sessions_closed = 0
        self.session_clicks = 0
        # close->absorb latency histogram (VERDICT r4 #5: config #4 must
        # carry a latency number like every other workload, core.clj:149)
        self.lat_hist = jnp.zeros((LAT_BINS,), jnp.int32)
        # Sessions keep NO window ring: the inherited span guard (sized
        # for ring reuse) would force wide catchup groups down the
        # per-batch path for nothing — let the scan fold whole chunks.
        self._span_guard = 2**31 - 1
        # per-chunk candidate-table salt: a sequence number, NOT wall
        # clock — async dispatch can issue several chunks per ms, and a
        # repeated salt would let one hash collision shadow the same
        # key pair across all of them
        self._scan_seq = 0

    ENGINE_FAMILY = "session_cms"
    # The fused scan keeps session windowing + CMS + ring + counters on
    # device for a whole chunk (no per-batch host syncs).
    SCAN_SUPPORTED = True
    SCAN_COLUMNS = ("user_idx", "event_type", "event_time", "valid")

    # Counters live as device scalars so absorbing a batch never blocks;
    # reading them (snapshot/close/stats) materializes.
    @property
    def sessions_closed(self) -> int:
        return int(self._closed_dev)

    @sessions_closed.setter
    def sessions_closed(self, v: int) -> None:
        self._closed_dev = jnp.int32(v)

    @property
    def session_clicks(self) -> int:
        return int(self._clicks_dev)

    @session_clicks.setter
    def session_clicks(self, v: int) -> None:
        self._clicks_dev = jnp.int32(v)

    def _device_scan(self, user_idx, event_type, event_time, valid) -> None:
        self._scan_seq += 1
        (self.state, self.cms, self.topk, self._closed_dev,
         self._clicks_dev, self.lat_hist) = _session_cms_scan(
            self.state, self.cms, self.topk, self._closed_dev,
            self._clicks_dev, self.lat_hist, self._now_rel(),
            jnp.int32(self._scan_seq),
            user_idx, event_type, event_time, valid,
            gap_ms=self.gap_ms, lateness_ms=self.lateness)

    def _cms_shape(self) -> tuple[int, int]:
        """[D, Wd] of the primary counter plane, any family."""
        t = (self.cms.fat.table if isinstance(self.cms, cms.CMS2State)
             else self.cms.table)
        return int(t.shape[0]), int(t.shape[1])

    def snapshot(self, offset: int):
        from streambench_tpu.checkpoint import Snapshot

        self._snapshot_sync()
        meta = self._snapshot_meta()
        depth, width = self._cms_shape()
        meta.update(gap_ms=self.gap_ms, user_capacity=self.user_capacity,
                    cms_depth=depth, cms_width=width,
                    cms_total=int(cms.sk_total(self.cms)),
                    cms_mode=self.cms_mode,
                    cms_stages=self.cms_stages,
                    sessions_closed=self.sessions_closed,
                    session_clicks=self.session_clicks)
        # family state rides extras: the fixed int32 table, the SALSA
        # uint8 plane + its merge bitmaps, or fat + small stages
        if self.cms_mode == "salsa":
            sketch = {"cms_table": np.asarray(self.cms.table),
                      "cms_m1": np.asarray(self.cms.m1),
                      "cms_m2": np.asarray(self.cms.m2)}
        elif self.cms_stages == 2:
            sketch = {"cms_table": np.asarray(self.cms.fat.table),
                      "cms_small": np.asarray(self.cms.small)}
        else:
            sketch = {"cms_table": np.asarray(self.cms.table)}
        return self._xo_decorate(Snapshot(
            offset=offset, meta=meta,
            counts=np.zeros((0, 0), np.int32),
            window_ids=np.zeros((0,), np.int32),  # no window ring here
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped),
            extra={"sess_last": np.asarray(self.state.last_time),
                   "sess_start": np.asarray(self.state.sess_start),
                   "sess_clicks": np.asarray(self.state.clicks),
                   **sketch,
                   "hh_keys": np.asarray(self.topk.keys),
                   "hh_ests": np.asarray(self.topk.ests),
                   "lat_hist": np.asarray(self.lat_hist),
                   **self._intern_extra()},
        ))

    def restore(self, snap) -> None:
        depth, width = self._cms_shape()
        self._check_geometry(snap, extra=dict(
            gap_ms=self.gap_ms, user_capacity=self.user_capacity,
            cms_depth=depth, cms_width=width,
            cms_stages=self.cms_stages))
        # mode is a string — checked here, not via the int-comparing
        # _check_geometry extra dict (legacy snapshots predate the key
        # and are implicitly "fixed")
        snap_mode = str(snap.meta.get("cms_mode", "fixed"))
        if snap_mode != self.cms_mode:
            raise ValueError(
                f"checkpoint cms_mode={snap_mode!r} != engine "
                f"{self.cms_mode!r}; restart with the original "
                "jax.cms.mode or discard the checkpoint")
        self.state = session.SessionState(
            last_time=jnp.asarray(snap.extra["sess_last"]),
            sess_start=jnp.asarray(snap.extra["sess_start"]),
            clicks=jnp.asarray(snap.extra["sess_clicks"]),
            watermark=jnp.int32(snap.watermark),
            dropped=jnp.int32(snap.dropped))
        if self.cms_mode == "salsa":
            self.cms = salsa.SalsaState(
                table=jnp.asarray(snap.extra["cms_table"]),
                m1=jnp.asarray(snap.extra["cms_m1"]),
                m2=jnp.asarray(snap.extra["cms_m2"]),
                total=jnp.int32(snap.meta["cms_total"]))
        elif self.cms_stages == 2:
            self.cms = cms.CMS2State(
                fat=cms.CMSState(
                    table=jnp.asarray(snap.extra["cms_table"]),
                    total=jnp.int32(snap.meta["cms_total"])),
                small=jnp.asarray(snap.extra["cms_small"]))
        else:
            self.cms = cms.CMSState(
                table=jnp.asarray(snap.extra["cms_table"]),
                total=jnp.int32(snap.meta["cms_total"]))
        self.sessions_closed = int(snap.meta["sessions_closed"])
        self.session_clicks = int(snap.meta["session_clicks"])
        self.lat_hist = (jnp.asarray(snap.extra["lat_hist"])
                         if "lat_hist" in snap.extra
                         else jnp.zeros((LAT_BINS,), jnp.int32))
        self._restore_interns(snap)
        self._restore_host(snap)
        if "hh_keys" in snap.extra:
            self.topk = cms.TopKState(
                keys=jnp.asarray(snap.extra["hh_keys"]),
                ests=jnp.asarray(snap.extra["hh_ests"]))
        else:
            # Legacy snapshot (pre-candidate-ring): seed the ring with a
            # ONE-TIME scan of the restored intern universe, or every
            # pre-crash heavy hitter would vanish from reports until it
            # happened to reappear.  Interns must be restored first.
            self._seed_topk_from_universe()

    def _seed_topk_from_universe(self, chunk: int = 8192) -> None:
        n = self.encoder.num_interned_users()
        for off in range(0, n, chunk):
            keys = np.zeros(chunk, np.int32)
            width = min(chunk, n - off)
            keys[:width] = np.arange(off, off + width, dtype=np.int32)
            mask = np.zeros(chunk, bool)
            mask[:width] = True
            self.topk = cms.update_topk(self.cms, self.topk,
                                        jnp.asarray(keys),
                                        jnp.asarray(mask))

    def _absorb(self, closed: session.ClosedSessions) -> None:
        self.cms = cms.sk_update(self.cms, closed.user, closed.clicks,
                                 closed.valid)
        self.topk = cms.update_topk(self.cms, self.topk, closed.user,
                                    closed.valid)
        # device-scalar counters: no host sync on the hot path
        self._closed_dev = self._closed_dev + jnp.sum(
            closed.valid.astype(jnp.int32))
        self._clicks_dev = self._clicks_dev + jnp.sum(
            jnp.where(closed.valid, closed.clicks, 0))

    def _device_step(self, batch) -> None:
        valid = jnp.asarray(batch.valid)
        tm = jnp.asarray(batch.event_time)
        self.state, in_batch, carried = session.step(
            self.state, jnp.asarray(batch.user_idx),
            jnp.asarray(batch.event_type), tm, valid,
            gap_ms=self.gap_ms, lateness_ms=self.lateness)
        # closures determined by this batch's evidence: latency = host
        # stamp at dispatch minus the batch's newest event time
        det_lat = jnp.maximum(
            self._now_rel() - jnp.max(jnp.where(valid, tm, wc.NEG)), 0)
        for closed in (in_batch, carried):
            self._absorb(closed)
            self.lat_hist = _hist_scalar(self.lat_hist, det_lat,
                                         closed.valid)

    def _drain_device(self) -> None:
        self.state, expired = session.flush(
            self.state, gap_ms=self.gap_ms, lateness_ms=self.lateness)
        self._absorb(expired)
        # time-expired closures became determinable when the watermark
        # passed end + gap + lateness; latency = host stamp minus that
        due = expired.end + (self.gap_ms + self.lateness)
        self.lat_hist = _hist_rows(
            self.lat_hist, jnp.maximum(self._now_rel() - due, 0),
            expired.valid)
        self._span_start = None

    def flush(self, time_updated: int | None = None, *,
              final: bool = False) -> int:
        self._drain_device()
        return 0  # sessions have no canonical window rows

    def latency_quantile(self, qs) -> tuple[list[float], int]:
        """Close->absorb latency quantiles (ms) from the device
        histogram, linearly interpolated within 250 ms bins; the
        overflow bin reports its lower edge.  Returns ``(values,
        total_sessions_sampled)``."""
        hist = np.asarray(self.lat_hist).astype(np.int64)
        total = int(hist.sum())
        if total == 0:
            return [], 0
        cum = np.cumsum(hist)
        out = []
        for q in qs:
            target = q * total
            b = int(np.searchsorted(cum, target, side="left"))
            b = min(b, LAT_BINS - 1)
            prev = int(cum[b - 1]) if b else 0
            frac = ((target - prev) / max(int(hist[b]), 1)
                    if b < LAT_BINS - 1 else 0.0)
            out.append((b + min(max(frac, 0.0), 1.0)) * LAT_BIN_MS)
        return out, total

    def heavy_hitters(self) -> list[tuple[str, int]]:
        """Top-k (user, estimated clicks), est > 0 only.

        Candidates come from the device-side ring (bounded), re-queried
        against the final CMS so early entries report current counts;
        only the winning <=k ids are reverse-looked-up to user names.
        """
        ring_keys = np.asarray(self.topk.keys)
        cand = ring_keys[ring_keys >= 0]
        if cand.size == 0:
            return []
        vals, idx = cms.heavy_hitters(self.cms, jnp.asarray(cand),
                                      k=min(self.top_k, int(cand.size)))
        vals = np.asarray(vals)
        idx = np.asarray(idx)
        out = []
        for v, i in zip(vals, idx):
            if v > 0:
                u = self.encoder.user_key(int(cand[int(i)]))
                out.append((u.decode() if isinstance(u, bytes) else u,
                            int(v)))
        return out

    def _write_heavy_hitters(self) -> None:
        """Top-k estimates -> Redis hash ``<redis.hashtable>_hh``."""
        if self.redis is not None and self.cfg.redis_hashtable:
            table = f"{self.cfg.redis_hashtable}_hh"
            cmds = [("HSET", table, user, str(est))
                    for user, est in self.heavy_hitters()]
            if cmds:
                self.redis.pipeline_execute(cmds)

    def close(self) -> None:
        self.state, final = session.flush(
            self.state, gap_ms=self.gap_ms, lateness_ms=self.lateness,
            force=True)
        self._absorb(final)
        self._write_heavy_hitters()

    def sketch_summary(self, merges: bool = False) -> dict:
        """Sketch-memory census for the stats line / obs report rows
        (ISSUE 13): family + measured state bytes (host-side ``nbytes``
        reads — no device sync, safe at sampler cadence).  With
        ``merges=True`` (close-time/bench callers only) the SALSA
        bitmap planes are pulled and the widened-counter counts added —
        that read blocks on in-flight dispatches, keep it off the
        per-tick path."""
        from streambench_tpu.obs.devmem import state_nbytes

        out = {"mode": self.cms_mode, "stages": self.cms_stages,
               "state_bytes": state_nbytes(self.cms)}
        if merges and self.cms_mode == "salsa":
            out.update(salsa.stats(self.cms))
        return out

    @property
    def dropped(self) -> int:
        return int(self.state.dropped)
