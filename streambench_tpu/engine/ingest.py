"""Staged ingest pipeline: overlap journal read, encode, and device dispatch.

The round-5 bench showed the catchup hot path is HOST-bound, not
device-bound: at the 65,536-event chunk, encode was 7.19 ms of the
8.93 ms pipelined chunk time (~80%) while device compute was ~1.7 ms
(``BENCH_r05.json``).  The cause is structural — ``StreamRunner`` ran
read -> encode -> dispatch serially in one loop, so while the encode
pool chewed a chunk nobody was reading the journal, and while the loop
polled the journal the encode workers sat idle.  This module is the
input-pipeline prefetcher a training stack would use for the same
problem (and the self-adjusting-ingest framing of SALSA, PAPERS.md):

- **stage 1, reader thread** — tails the journal into a bounded *block
  queue*: raw byte blocks when the engine supports block ingest, line
  lists otherwise.  In paced mode it owns the runner's batching policy
  (adaptive target under backlog, ``buffer_timeout_ms`` for partial
  groups); in catchup mode it reads chunk-sized blocks and emits
  :data:`EOF` at the first dry poll, exactly like the serial loop.
- **stage 2, encode thread** — carves/encodes each block into
  ``EncodedBatch`` groups (``engine.encode_raw_block`` /
  ``engine.encode_chunk_lines`` — the encode pool still parallelizes
  WITHIN a block) onto a bounded *batch queue*.
- **stage 3, the host loop** — ``get()``s ready groups and does only
  device dispatch (``engine.fold_batches``) + flush.

Ordering is strict journal FIFO: one thread per stage, one consumer, so
folds happen in read order — the span guard and ``_note_watermark``
host mirror assume exactly that.  Backpressure comes from the queue
bounds (a slow device stalls encode, a slow encode stalls the reader).

Checkpoint consistency: ``commit(item)`` (called by the host AFTER
folding) advances the *folded position* — the reader offset covering
exactly the blocks already folded.  ``quiesce()`` additionally parks
both stage threads at a work-item boundary (each stage does its real
work under a stage lock; queue waits happen outside it), so a snapshot
can serialize encoder state (base time, intern tables) without racing
the encode thread.  In-flight prefetched blocks are simply replayable:
their bytes sit past the folded offset, which is the at-least-once
contract ``chaos.verify`` checks.
"""

from __future__ import annotations

import queue
import threading
import time

from streambench_tpu.utils.ids import now_ms


class _Sentinel:
    def __init__(self, name: str) -> None:
        self._name = name

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return self._name


#: End-of-stream marker ``get()`` returns once: the reader hit a dry
#: poll in catchup mode, or ``finish()`` drained the paced stages.
EOF = _Sentinel("<ingest EOF>")


class IngestItem:
    """One journal read unit flowing through the stages.

    ``payload`` is the raw read (bytes in block mode, a line list
    otherwise) until the encode stage replaces it with ``batches``;
    ``end_pos`` is the reader position (scalar offset, or the offsets
    vector of a ``MultiReader``) immediately after the reads that formed
    this item — the value ``commit`` publishes as the folded position.
    ``read_ms`` is the wall stamp of the FIRST read that contributed
    (obs.lifecycle attribution: with read-ahead the gap between reading
    and encoding is real, so the stamp must travel with the item).
    """

    __slots__ = ("payload", "records", "end_pos", "batches", "read_ms")

    def __init__(self, payload, records: int, end_pos,
                 read_ms: "int | None" = None) -> None:
        self.payload = payload
        self.records = records
        self.end_pos = end_pos
        self.batches: list = []
        self.read_ms = read_ms


class IngestPipeline:
    """Three overlapped ingest stages over one (engine, reader) pair.

    The host loop drives stage 3::

        pipe = IngestPipeline(engine, reader, ...)
        while ...:
            item = pipe.get(timeout_s=0.05)
            if item is ingest.EOF: break
            if item is None: continue          # stages still working
            engine.fold_batches(item.batches)
            pipe.commit(item)                  # folded position advances
        pipe.close()

    One pipeline drives one run attempt; build a fresh one per attempt
    (the supervisor's fresh-runner rule extends to the stages).
    """

    def __init__(self, engine, reader, *,
                 batch_size: int,
                 chunk_records: int,
                 buffer_timeout_ms: int | None = None,
                 catchup: bool = False,
                 est_event_bytes: int = 256,
                 block_queue: int = 4,
                 batch_queue: int = 4,
                 poll_interval_s: float = 0.001,
                 flightrec=None, spans=None) -> None:
        self.engine = engine
        self.reader = reader
        # crash flight recorder (obs.flightrec or None): stage errors
        # and first-stall events land in the postmortem ring
        self.flightrec = flightrec
        # span tracer (obs.spans or None): non-empty reads and encode
        # stage work land as "ingest_read"/"ingest_encode" spans on
        # their own threads — the encode spans the engine's Tracer sink
        # already forwards show WHAT was encoded; these show the stage
        # residency around it
        self.spans = spans
        self.batch_size = max(int(batch_size), 1)
        self.chunk_records = max(int(chunk_records), self.batch_size)
        self.buffer_timeout_ms = buffer_timeout_ms
        self.catchup = catchup
        self.est_event_bytes = max(int(est_event_bytes), 1)
        self.poll_interval_s = poll_interval_s
        self.block_mode = (getattr(engine, "supports_block_ingest", False)
                           and hasattr(reader, "poll_block"))
        self._block_q: queue.Queue = queue.Queue(maxsize=max(block_queue, 1))
        self._batch_q: queue.Queue = queue.Queue(maxsize=max(batch_queue, 1))
        self._stop = threading.Event()
        self._finish = threading.Event()
        # Stage locks: held only while a stage touches the reader or the
        # encoder (never across a queue wait), so quiesce() can park both
        # stages by acquiring them — bounded by one work item, and
        # deadlock-free because the host holds neither during get().
        self._reader_lock = threading.Lock()
        self._encode_lock = threading.Lock()
        self._error: BaseException | None = None
        # Stall/starvation accounting (telemetry): each counter has ONE
        # writer thread, so plain int += is safe under the GIL.
        self.reader_stalls = 0     # reader blocked on a full block queue
        self.encode_stalls = 0     # encode blocked on a full batch queue
        self.encode_starved = 0    # encode waited on an empty block queue
        self.dispatch_starved = 0  # host get() timed out (stages behind)
        self.records_read = 0
        self.records_folded = 0
        self.read_ms_total = 0.0
        self.encode_ms_total = 0.0
        self.last_data_ts = time.monotonic()
        self.closed = False
        self._folded_pos = self._position()
        self._reader_thread = threading.Thread(
            target=self._reader_main, daemon=True, name="ingest-reader")
        self._encode_thread = threading.Thread(
            target=self._encode_main, daemon=True, name="ingest-encode")
        self._reader_thread.start()
        self._encode_thread.start()

    # ------------------------------------------------------------------
    def _position(self):
        """Reader position: scalar byte offset, or a COPY of the
        per-partition offsets vector (``MultiReader``)."""
        try:
            return self.reader.offset
        except AttributeError:
            return list(self.reader.offsets)

    def _fail(self, err: BaseException) -> None:
        """Record a stage failure for the host to re-raise from get()."""
        if self._error is None:
            self._error = err
        if self.flightrec is not None:
            self.flightrec.record("ingest_error", error=repr(err),
                                  block_queue=self._block_q.qsize(),
                                  batch_queue=self._batch_q.qsize())
        self._stop.set()

    def _put(self, q: queue.Queue, item, counter: str | None) -> bool:
        """Bounded put that stays interruptible (close()) and counts the
        first time each item had to wait on a full queue."""
        stalled = False
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                if not stalled and counter is not None:
                    stalled = True
                    setattr(self, counter, getattr(self, counter) + 1)
                    if self.flightrec is not None:
                        self.flightrec.record(
                            "ingest_stall", stage=counter,
                            block_queue=self._block_q.qsize(),
                            batch_queue=self._batch_q.qsize())
        return False

    # -- stage 1: reader ----------------------------------------------
    def _reader_main(self) -> None:
        try:
            if self.catchup:
                self._reader_catchup()
            else:
                self._reader_paced()
        except BaseException as e:  # delivered to the host via get()
            self._fail(e)

    def _read_once(self, room: int) -> tuple[object, int, bool]:
        """One bounded journal read under the reader lock.  Returns
        (payload, records, full_read) with the SAME backlog judgment as
        the serial loop: in block mode a NON-EMPTY read that nearly
        filled its byte budget means more data is waiting (an empty read
        must never count as full, or a tiny budget at room == 1 would
        busy-spin on an idle stream)."""
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        with self._reader_lock:
            if self.block_mode:
                budget = room * self.est_event_bytes
                data = self.reader.poll_block(budget)
                got = data.count(b"\n") if data else 0
                full = (got > 0
                        and len(data) >= budget - self.est_event_bytes)
            else:
                data = self.reader.poll(max_records=room)
                got = len(data)
                full = got >= room
        self.read_ms_total += (time.perf_counter() - t0) * 1e3
        if self.spans is not None and got:
            # only non-empty reads: at the 1 ms poll cadence, empty
            # polls would flood the bounded ring with nothing
            self.spans.add("ingest_read",
                           t0_ns, time.perf_counter_ns() - t0_ns,
                           cat="ingest", args={"records": got})
        return data, got, full

    def _reader_catchup(self) -> None:
        """Chunk-sized reads, EOF at the first dry poll (the serial
        ``run_catchup`` contract: a prewritten journal is drained)."""
        while not self._stop.is_set():
            data, got, _full = self._read_once(self.chunk_records)
            if not got:
                self._put(self._block_q, EOF, None)
                return
            pos = self._position()
            self.records_read += got
            self.last_data_ts = time.monotonic()
            if not self._put(self._block_q,
                             IngestItem(data, got, pos, read_ms=now_ms()),
                             "reader_stalls"):
                return

    def _reader_paced(self) -> None:
        """The streaming loop's batching policy, moved into the reader:
        adaptive target growth under backlog (full reads double toward
        one scan chunk, short reads snap back to one batch) and the
        ``buffer_timeout_ms`` partial-group dispatch."""
        pending: list = []
        pending_n = 0
        pending_since: float | None = None
        pending_read_ms: int | None = None   # first-read wall stamp
        pending_end = self._folded_pos
        target = self.batch_size
        while not self._stop.is_set():
            finishing = self._finish.is_set()
            got = 0
            if not finishing:
                room = max(target - pending_n, 1)
                data, got, full = self._read_once(room)
                now = time.monotonic()
                if got:
                    pending_end = self._position()
                    self.records_read += got
                    self.last_data_ts = now
                    if pending_since is None:
                        pending_since = now
                        pending_read_ms = now_ms()
                    pending_n += got
                    if self.block_mode:
                        pending.append(data)
                    else:
                        pending.extend(data)
                    if full:            # backlog: scale the batch up
                        target = min(target * 2, self.chunk_records)
                    elif pending_n < self.batch_size:
                        target = self.batch_size
                elif pending_n < self.batch_size:
                    target = self.batch_size
            else:
                now = time.monotonic()
            timeout_old = (pending_since is not None
                           and self.buffer_timeout_ms is not None
                           and (now - pending_since) * 1000
                           >= self.buffer_timeout_ms)
            if pending and (pending_n >= target or timeout_old
                            or finishing):
                payload = (b"".join(pending) if self.block_mode
                           else pending)
                item = IngestItem(payload, pending_n, pending_end,
                                  read_ms=pending_read_ms)
                pending, pending_n, pending_since = [], 0, None
                pending_read_ms = None
                if not self._put(self._block_q, item, "reader_stalls"):
                    return
            elif finishing:
                self._put(self._block_q, EOF, None)
                return
            elif not got:
                time.sleep(self.poll_interval_s)

    # -- stage 2: encode ----------------------------------------------
    def _encode_main(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    item = self._block_q.get(timeout=0.05)
                except queue.Empty:
                    self.encode_starved += 1
                    continue
                if item is EOF:
                    self._put(self._batch_q, EOF, None)
                    return
                t0 = time.perf_counter()
                t0_ns = time.perf_counter_ns()
                with self._encode_lock:
                    if self.block_mode:
                        item.batches = self.engine.encode_raw_block(
                            item.payload)
                    else:
                        item.batches = self.engine.encode_chunk_lines(
                            item.payload)
                item.payload = None   # free the raw bytes early
                self.encode_ms_total += (time.perf_counter() - t0) * 1e3
                if self.spans is not None:
                    self.spans.add(
                        "ingest_encode",
                        t0_ns, time.perf_counter_ns() - t0_ns,
                        cat="ingest", args={"records": item.records})
                if item.read_ms is not None and item.batches:
                    # attribution stamps (obs.lifecycle): the engine's
                    # encode halves default the read stamp to encode
                    # time; with read-ahead the TRUE read time is the
                    # item's — override so ingest_ms/encode_ms split at
                    # the real boundary
                    lc = getattr(self.engine, "_obs_lifecycle", None)
                    if lc is not None:
                        for b in item.batches:
                            b._lc_read_ms = item.read_ms
                if not self._put(self._batch_q, item, "encode_stalls"):
                    return
        except BaseException as e:
            self._fail(e)

    # -- stage 3 surface (host loop) -----------------------------------
    def get(self, timeout_s: float = 0.05):
        """Next encoded :class:`IngestItem` in journal order, ``EOF`` at
        end-of-stream, or ``None`` when nothing is ready yet.  Re-raises
        a stage thread's failure here, on the host thread, preserving
        the original exception type (the supervisor's ``catch`` surface
        must see the same errors the serial loop would have raised)."""
        if self._error is not None:
            err, self._error = self._error, None
            raise err
        try:
            return self._batch_q.get(timeout=timeout_s)
        except queue.Empty:
            self.dispatch_starved += 1
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return None

    def commit(self, item: IngestItem) -> None:
        """Publish ``item`` as folded: its end position becomes the
        checkpointable offset.  Call strictly AFTER ``fold_batches`` —
        committing first would let a crash-between skip the block."""
        self._folded_pos = item.end_pos
        self.records_folded += item.records

    def position(self):
        """Reader position covering exactly the folded blocks (scalar or
        per-partition vector) — the checkpoint/crash-offset unit."""
        return self._folded_pos

    def quiesce(self):
        """Park both stage threads at a work-item boundary and return the
        folded position.  While quiesced, nothing touches the reader or
        the encoder, so a snapshot can serialize encoder state safely;
        in-flight items keep sitting in the queues (their bytes are past
        the returned offset — replayable, never skippable).  Pair with
        :meth:`resume`."""
        self._reader_lock.acquire()
        self._encode_lock.acquire()
        return self._folded_pos

    def resume(self) -> None:
        self._encode_lock.release()
        self._reader_lock.release()

    def finish(self) -> None:
        """Ask the paced reader to emit its partial pending block and
        EOF (the serial loop's trailing ``if pending: dispatch()``)."""
        self._finish.set()

    def drained(self) -> bool:
        """True when every record the reader has seen was folded."""
        return self.records_folded >= self.records_read

    def idle_for(self) -> float:
        """Seconds since the reader last returned data (idle-timeout
        input; folds of already-read data don't reset it, but they keep
        ``drained()`` False, which the idle check also requires)."""
        return time.monotonic() - self.last_data_ts

    def close(self) -> None:
        """Stop both stages and join them.  Uncommitted in-flight items
        are discarded — their bytes are past the folded position, so a
        resume replays them (never loses them)."""
        self._stop.set()
        for t in (self._reader_thread, self._encode_thread):
            if t.is_alive():
                t.join(timeout=5)
        self.closed = True

    # ------------------------------------------------------------------
    def telemetry(self) -> dict:
        """Point-in-time stage health (obs sampler / bench artifact):
        queue depths, stall/starvation counters, per-stage busy time.
        With device decode active the encode stage is only the layout
        probe — its row counters ride along so the artifact can show
        where the encode work actually went."""
        dd = getattr(self.engine, "_devdecode", None)
        extra = ({"device_decode": dd.telemetry()}
                 if dd is not None else {})
        return {
            **extra,
            "block_queue_depth": self._block_q.qsize(),
            "batch_queue_depth": self._batch_q.qsize(),
            "reader_stalls": self.reader_stalls,
            "encode_stalls": self.encode_stalls,
            "encode_starved": self.encode_starved,
            "dispatch_starved": self.dispatch_starved,
            "records_read": self.records_read,
            "records_folded": self.records_folded,
            "read_ms_total": round(self.read_ms_total, 3),
            "encode_ms_total": round(self.encode_ms_total, 3),
        }
