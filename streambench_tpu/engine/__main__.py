"""Engine process CLI — the peer of one engine-topology launch.

In the reference, ``START_FLINK_PROCESSING`` submits the topology jar to a
running cluster (``stream-bench.sh:254``: ``flink run … --confPath
conf/localConf.yaml``) and ``STOP_*_PROCESSING`` cancels it.  Here the
"topology" is one OS process: it loads the config, builds the
``AdAnalyticsEngine`` (or its sharded variant), tails the broker topic, and
flushes the canonical Redis window schema until it receives SIGTERM, at
which point it drains, closes (final flush + fork-style latency dump,
``AdvertisingTopologyNative.java:521-532``), and prints one JSON stats line.

    python -m streambench_tpu.engine --confPath conf/localConf.yaml \
        --workdir RUN_DIR [--brokerDir DIR] [--duration S] [--sharded]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

from streambench_tpu.utils.platform import pin_jax_platform

pin_jax_platform()

from streambench_tpu.config import ConfigError, find_and_read_config_file
from streambench_tpu.datagen import gen
from streambench_tpu.engine.pipeline import AdAnalyticsEngine
from streambench_tpu.engine.runner import StreamRunner
from streambench_tpu.io.fakeredis import make_store
from streambench_tpu.io.kafka import make_broker
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.io.resp import RespClient


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="streambench-engine")
    p.add_argument("--confPath", default="./benchmarkConf.yaml")
    p.add_argument("--workdir", default=".",
                   help="where the id/mapping files from -n/-s live")
    p.add_argument("--brokerDir", default=None)
    p.add_argument("--duration", type=float, default=None,
                   help="seconds to run (default: until SIGTERM)")
    p.add_argument("--idleTimeout", type=float, default=None,
                   help="exit after this many idle seconds (catchup runs)")
    p.add_argument("--maxEvents", type=int, default=None)
    p.add_argument("--catchup", action="store_true",
                   help="drain the journal at full speed, then exit")
    p.add_argument("--sharded", action="store_true",
                   help="run the mesh-sharded engine (jax.mesh.* config)")
    p.add_argument("--engine", default="exact",
                   choices=("exact", "hll", "sliding", "session",
                            "reach", "hllx"),
                   help="aggregation engine: exact window counts "
                        "(default), HLL distinct users, sliding-window + "
                        "t-digest quantiles, session windows + "
                        "count-min heavy hitters (BASELINE configs "
                        "#1-#4), cumulative MinHash∪HLL reach "
                        "sketches served live over pub/sub (README "
                        "\"Reach serving\"), or the hyper-extended HLL "
                        "ladder answering distinct-count AND "
                        "frequency-moment queries from one register "
                        "plane (README \"Sketch memory\")")
    p.add_argument("--checkpointDir", default=None,
                   help="enable (offset, state) snapshots here; on start, "
                        "resume from the newest one if present")
    p.add_argument("--traceDir", default=None,
                   help="capture a jax.profiler device trace here")
    p.add_argument("--microbatch", action="store_true",
                   help="run the fork's count-based barrier-aligned window "
                        "mode (window.size / map.partitions) over the "
                        "broker topic, then exit")
    p.add_argument("--tenants", default=None,
                   help="run the multi-tenant host instead of one engine: "
                        "\"name:kind,...\" (kinds: exact/hll/sliding/"
                        "session/reach/hllx; README \"Multi-tenant "
                        "operation\").  Every tenant tails the shared "
                        "topic with its own engine + tenant= metric "
                        "namespace; overrides jax.tenants")
    return p


def load_mapping(cfg, workdir: str) -> tuple[dict[str, str], list[str] | None]:
    """Resolve the ad->campaign join table the way the fork does: an explicit
    ``ad_to_campaign_path`` wins (``AdvertisingTopologyNative.java:47-56``),
    else the workdir files written by the generator's ``-n``/``-s`` modes."""
    path = cfg.ad_to_campaign_path or os.path.join(
        workdir, gen.AD_TO_CAMPAIGN_FILE)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"ad->campaign mapping not found at {path}; run the generator "
            "-n or -s mode first (or set ad_to_campaign_path)")
    mapping = gen.load_ad_mapping_file(path)
    ids = gen.load_ids(workdir)
    campaigns = ids[0] if ids else None
    return mapping, campaigns


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        cfg = find_and_read_config_file(args.confPath)
    except ConfigError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    mapping, campaigns = load_mapping(cfg, args.workdir)

    # multi-tenant host (obs layer 9): N topologies, one process, one
    # device — delegates the whole run like --microbatch does (the
    # host owns its engines, sinks and obs wiring)
    if args.tenants or cfg.jax_tenants:
        from streambench_tpu.engine.tenants import run_tenants_cli

        return run_tenants_cli(cfg, args, mapping, campaigns)

    if cfg.redis_host == ":inprocess:":
        redis = as_redis(make_store())
    else:
        redis = RespClient(cfg.redis_host, cfg.redis_port)

    if args.microbatch:
        if args.engine in ("sliding", "session", "reach", "hllx"):
            raise SystemExit(
                f"--microbatch has no count-window form of --engine "
                f"{args.engine} (sliding needs a time axis, session a gap "
                f"axis, reach/hllx are cumulative); supported: exact, hll")
        from streambench_tpu.engine.microbatch import run_microbatch

        broker = make_broker(cfg.kafka_bootstrap_servers,
                             args.brokerDir
                             or os.path.join(args.workdir, "broker"),
                             fake=cfg.kafka_fake)
        merged, results = run_microbatch(
            cfg, broker, mapping, campaigns=campaigns, redis=redis,
            engine=args.engine, checkpoint_dir=args.checkpointDir)
        lats = sorted(lat for r in results for lat in r.latency.values())
        print(json.dumps({
            "engine": args.engine,
            "windows": len(merged),
            "events": sum(r.events for r in results),
            "partitions": len(results),
            "total_views": int(sum(int(c.sum()) for c in merged.values())),
            "p50_latency_ms": lats[len(lats) // 2] if lats else None,
        }), flush=True)
        return 0

    def make_engine(r) -> AdAnalyticsEngine:
        if args.sharded:
            from streambench_tpu.parallel import (
                ShardedHLLEngine,
                ShardedSessionCMSEngine,
                ShardedSlidingTDigestEngine,
                ShardedWindowEngine,
                mesh_from_config,
            )
            from streambench_tpu.parallel.reach import ShardedReachEngine
            cls = {"exact": ShardedWindowEngine,
                   "hll": ShardedHLLEngine,
                   "sliding": ShardedSlidingTDigestEngine,
                   "session": ShardedSessionCMSEngine,
                   "reach": ShardedReachEngine}.get(args.engine)
            if cls is None:
                raise SystemExit(f"--sharded supports exact/hll/sliding/"
                                 f"session/reach, not --engine "
                                 f"{args.engine}")
            return cls(cfg, mapping, mesh_from_config(cfg),
                       campaigns=campaigns, redis=r)
        if args.engine != "exact":
            from streambench_tpu.engine.sketches import (
                HLLDistinctEngine,
                HLLXEngine,
                ReachSketchEngine,
                SessionCMSEngine,
                SlidingTDigestEngine,
            )
            cls = {"hll": HLLDistinctEngine,
                   "sliding": SlidingTDigestEngine,
                   "session": SessionCMSEngine,
                   "reach": ReachSketchEngine,
                   "hllx": HLLXEngine}[args.engine]
            return cls(cfg, mapping, campaigns=campaigns, redis=r)
        return AdAnalyticsEngine(cfg, mapping, campaigns=campaigns, redis=r)

    engine = make_engine(redis)

    broker = make_broker(cfg.kafka_bootstrap_servers,
                         args.brokerDir
                         or os.path.join(args.workdir, "broker"),
                         fake=cfg.kafka_fake)
    broker.create_topic(cfg.kafka_topic)
    # Dead-letter queue (off by default): malformed events are journaled
    # to <topic>-deadletter instead of only bumping bad_lines, so they
    # stay replayable after a parser fix (the reference drops bad tuples
    # silently).  Wired to the primary encoder; parallel encode pool
    # workers still count rejects but journal only from the primary.
    deadletter = None
    if cfg.jax_deadletter_enabled:
        deadletter = broker.writer(f"{cfg.kafka_topic}-deadletter")
        engine.encoder.set_deadletter(deadletter)
    # Checkpointing works for every engine family (sketch snapshots carry
    # their device state + intern tables, engine.sketches) and for
    # multi-partition topics (per-partition offset vector, checkpoint.py).
    checkpointer = None
    n_parts = len(broker.partitions(cfg.kafka_topic))
    if args.checkpointDir:
        from streambench_tpu.checkpoint import Checkpointer

        checkpointer = Checkpointer(args.checkpointDir)
    # one consumer over the whole topic, every partition (engines in the
    # reference likewise subscribe to all of ad-events)
    reader = (broker.multi_reader(cfg.kafka_topic) if n_parts > 1
              else broker.reader(cfg.kafka_topic))
    # Crash flight recorder (obs.flightrec, default-off): a bounded ring
    # the runner/ingest stages feed at flush cadence, dumped to
    # <workdir>/flight_<reason>.jsonl on crash, fatal exception, or
    # SIGTERM — the run's black box when there is no exit stats line.
    flightrec = None
    if cfg.jax_obs_flightrec:
        from streambench_tpu.obs import FlightRecorder

        flightrec = FlightRecorder(
            args.workdir, capacity=cfg.jax_obs_flightrec_capacity)
    # Span tracer (obs.spans, default-off): bounded thread-aware ring of
    # closed stage/read spans, dumped as perfetto-loadable Chrome trace
    # JSON at exit; flight-recorder dumps embed its tail so a crash
    # postmortem carries the final seconds' timing context.
    spans = None
    if cfg.jax_obs_spans:
        from streambench_tpu.obs import SpanTracer

        spans = SpanTracer(capacity=cfg.jax_obs_spans_capacity)
        if flightrec is not None:
            flightrec.span_source = spans.tail
    runner = StreamRunner(engine, reader, checkpointer=checkpointer,
                          flightrec=flightrec, spans=spans)
    if runner.resume():
        print(f"resumed from checkpoint: offset={runner._reader_position()} "
              f"events={engine.events_processed}", flush=True)

    def _on_sigterm(*_):
        if flightrec is not None:
            flightrec.record("signal", event="sigterm")
            flightrec.dump("sigterm")
        runner.stop()

    signal.signal(signal.SIGTERM, _on_sigterm)
    signal.signal(signal.SIGINT, lambda *_: runner.stop())

    # Pre-compile every device program (single step, all scan group
    # sizes, the drain) on a throwaway same-shape engine before announcing
    # readiness, so the load phase never races XLA compilation (~20-40 s
    # on first TPU use; a mid-run compile also starves co-located
    # producers on small hosts) — the JVM engines likewise deploy their
    # tasks before the harness starts the generator.
    warm = make_engine(None)
    warm.warmup()
    warm.close()
    del warm

    # Live telemetry (obs/, default-off): jax.metrics.interval.ms > 0
    # starts the sampler journaling snapshots to <workdir>/metrics.jsonl;
    # jax.metrics.port >= 0 serves the localhost Prometheus endpoint
    # (0 = ephemeral, the chosen port is printed below so harnesses and
    # the smoke test can scrape without a race).
    sampler = metrics_server = occupancy = slo = None
    xfer = shard = devmem = capture = query_obs = None
    registry = None
    slo_wanted = (cfg.jax_slo_p99_ms > 0 or cfg.jax_slo_rate_evps > 0
                  or (args.engine == "reach"
                      and cfg.jax_reach_slo_p99_ms > 0))
    query_obs_wanted = args.engine == "reach" and cfg.jax_obs_query
    if (cfg.jax_metrics_interval_ms > 0 or cfg.jax_metrics_port >= 0
            or cfg.jax_obs_lifecycle or cfg.jax_obs_spans
            or cfg.jax_obs_occupancy or slo_wanted
            or cfg.jax_obs_xfer or cfg.jax_obs_devmem
            or cfg.jax_obs_shard or cfg.jax_obs_capture
            or query_obs_wanted or cfg.jax_obs_fleet):
        from streambench_tpu.obs import (
            CaptureManager,
            DeviceMemoryLedger,
            MetricsRegistry,
            MetricsSampler,
            MetricsServer,
            OccupancySampler,
            ShardSkew,
            SloTracker,
            TransferLedger,
            engine_collector,
        )

        registry = MetricsRegistry()
        # jax.obs.occupancy: sampled block_until_ready-timed dispatches
        # -> the MEASURED device_busy_ratio, plus the recompile
        # detector.  mark_steady() waits until the data-path obs below
        # finish THEIR compiles (shard-stats kernel variants, devmem
        # analysis) so the steady-state counter's invariant stays zero.
        if cfg.jax_obs_occupancy:
            occupancy = OccupancySampler(
                registry, sample_every=cfg.jax_obs_occupancy_sample)
        # jax.obs.xfer: host->device transfer ledger — exact payload
        # bytes per dispatch by wire format + 1-in-N timed transfers
        if cfg.jax_obs_xfer:
            xfer = TransferLedger(registry,
                                  sample_every=cfg.jax_obs_xfer_sample)
        # jax.obs.shard: per-shard routed-row skew gauges (sharded
        # engines only — the flag is inert without --sharded)
        if cfg.jax_obs_shard and args.sharded:
            from streambench_tpu.parallel.mesh import CAMPAIGN_AXIS

            shard = ShardSkew(
                registry, n_shards=engine.mesh.shape[CAMPAIGN_AXIS])
        # jax.obs.lifecycle additionally attaches the per-window
        # attribution tracker (and, set alone, turns the sampler on at
        # its default cadence — attribution with no journal to land in
        # would be pointless; spans/occupancy/SLO likewise imply it)
        engine.attach_obs(registry, lifecycle=cfg.jax_obs_lifecycle,
                          spans=spans, occupancy=occupancy, xfer=xfer,
                          shard=shard)
        if shard is not None:
            # the shard-stats kernels are SEPARATE compiled programs the
            # throwaway warmup above never dispatched; compile them now
            # (warmup is state-neutral) so they can't land mid-run
            engine.warmup()
        # jax.obs.devmem: compiled-kernel memory_analysis footprints —
        # each costs an out-of-line compile (never shares the jit call
        # cache), so this runs exactly once, here, before mark_steady
        if cfg.jax_obs_devmem:
            devmem = DeviceMemoryLedger(registry)
            devmem.analyze_engine(engine)
        # NOTE: occupancy.mark_steady() is deferred until AFTER the
        # reach serving block below — the query server pre-compiles
        # its padded batch_query kernel at the first state push
        # (attach_reach), and that compile must count as warmup, not
        # as a steady-state violation.
        # jax.obs.query: per-query lifecycle attribution for the reach
        # serving tier (the query-side WindowLifecycle).  Built here so
        # the SLO tracker below can attach segment attribution to
        # breach events; the ReachQueryServer gets it further down.
        # With spans also on, the queue-wait/ingest-dispatch overlap
        # feeds streambench_reach_contention_ratio.
        if query_obs_wanted:
            from streambench_tpu.obs.queryattr import QueryLifecycle

            query_obs = QueryLifecycle(
                registry, slo_ms=cfg.jax_reach_slo_p99_ms,
                slowlog_max=cfg.jax_obs_query_slowlog,
                sample_every=cfg.jax_obs_query_sample, spans=spans)
            if occupancy is not None:
                # the contention numerator's production evidence: the
                # occupancy sampler's measured busy windows (async
                # ingest dispatch spans cover only the submit call)
                occupancy.busy_sink = query_obs.note_ingest_busy
        metrics_path = os.path.join(args.workdir, "metrics.jsonl")
        # fleet attribution (ISSUE 15): the engine CLI is the fleet's
        # single writer; role-stamping its journal lets the
        # FleetCollector merge it with replica journals unambiguously
        sampler = MetricsSampler(
            metrics_path,
            interval_ms=cfg.jax_metrics_interval_ms or 1000,
            registry=registry,
            max_bytes=cfg.jax_metrics_max_bytes,
            role="writer")
        sampler.add_collector(engine_collector(
            engine, reader=reader, runner=runner, registry=registry))
        # Kafka delivery ledger (ISSUE 20): when the broker is the
        # Kafka adapter its shared FaultCounters carry the
        # produced/delivered/redelivered accounting — journal it under
        # rec["kafka"] and mirror the headline instruments (predeclared
        # inside the collector, scrape-gap rule)
        if getattr(broker, "counters", None) is not None:
            from streambench_tpu.obs import kafka_collector

            sampler.add_collector(kafka_collector(
                broker.counters, lag=getattr(reader, "lag", None),
                registry=registry))
        if devmem is not None:
            sampler.add_collector(devmem.collect)
        # jax.obs.capture.*: bounded triggered profiler capture — SLO
        # breach transitions, SIGUSR2, or the startup one-shot fire a
        # short jax.profiler window into <workdir>/xprof_<ms>_<reason>/
        if cfg.jax_obs_capture:
            capture = CaptureManager(
                args.workdir,
                cooldown_s=cfg.jax_obs_capture_cooldown_s,
                max_captures=cfg.jax_obs_capture_max,
                window_s=cfg.jax_obs_capture_window_s,
                registry=registry, flightrec=flightrec,
                annotate=sampler.annotate)
            signal.signal(signal.SIGUSR2,
                          lambda *_: capture.trigger("sigusr2"))
        # SLO burn-rate tracking (obs.slo): collects AFTER the engine
        # collector so rec["events"]/["events_per_s"] feed the rate
        # objective; breach transitions are journaled as event records
        # and ticked into the flight recorder.
        if slo_wanted:
            slo = SloTracker(
                registry, p99_ms=cfg.jax_slo_p99_ms,
                rate_evps=cfg.jax_slo_rate_evps,
                reach_p99_ms=(cfg.jax_reach_slo_p99_ms
                              if args.engine == "reach" else 0),
                budget=cfg.jax_slo_budget, fast_s=cfg.jax_slo_fast_s,
                slow_s=cfg.jax_slo_slow_s,
                use_lifecycle=cfg.jax_obs_lifecycle,
                annotate=sampler.annotate, flightrec=flightrec,
                capture=capture, queryattr=query_obs)
            sampler.add_collector(slo.collect)
        sampler.start()
        endpoint = ""
        if cfg.jax_metrics_port >= 0:
            metrics_server = MetricsServer(registry,
                                           port=cfg.jax_metrics_port,
                                           refresh=sampler.collect_now)
            endpoint = f" endpoint={metrics_server.url}"
        print(f"metrics: interval={sampler.interval_ms}ms "
              f"jsonl={metrics_path}{endpoint}", flush=True)

    # Live reach-query serving (reach/; --engine reach only): one
    # pub/sub endpoint (WebSocket + JSON-lines on one port) with the
    # "reach" query verb routed through the bounded load-shedding
    # query server; the engine pushes sketch state at flush cadence.
    reach_ps = reach_srv = reach_store = reach_ship = None
    if args.engine == "reach":
        from streambench_tpu.dimensions.pubsub import PubSubServer
        from streambench_tpu.reach.cache import ReachQueryCache
        from streambench_tpu.reach.serve import ReachQueryServer

        reach_cache = (ReachQueryCache(cfg.jax_reach_cache_capacity,
                                       registry=registry)
                       if cfg.jax_reach_cache_capacity > 0 else None)
        reach_ps = PubSubServer(port=0).start()
        reach_srv = ReachQueryServer(
            list(engine.encoder.campaigns),
            depth=cfg.jax_reach_queue_depth, registry=registry,
            queryattr=query_obs, spans=spans, flightrec=flightrec,
            cache=reach_cache)
        reach_ps.register_query("reach", reach_srv.handle)
        engine.attach_reach(reach_srv)
        # replica snapshot shipping (ISSUE 14): append (epoch, planes,
        # watermark) records into <dir>/dimensions.log at the cadence;
        # replica processes tail it (streambench_tpu.reach.replica)
        if cfg.jax_reach_ship_dir:
            from streambench_tpu.dimensions.store import (
                DurableDimensionStore,
            )
            from streambench_tpu.reach.deltaship import (
                DELTA_AUTO_MIN_CAMPAIGNS,
                DeltaShipper,
            )
            from streambench_tpu.reach.replica import SnapshotShipper

            reach_store = DurableDimensionStore(cfg.jax_reach_ship_dir)
            # origin metadata (ISSUE 15): every shipped record names
            # this writer's pub/sub endpoint + pid, so fleet-mode
            # replicas can ping it for the clock-offset estimate and
            # the merged fleet view can attribute the record
            s_host, s_port = reach_ps.address
            # delta shipping (ISSUE 18): O(ΔC) dirty-row records
            # between periodic bases; "auto" turns it on where the
            # full gather actually hurts (large campaign counts)
            dmode = cfg.jax_reach_ship_delta
            use_delta = (dmode == "on"
                         or (dmode == "auto"
                             and engine.encoder.num_campaigns
                             >= DELTA_AUTO_MIN_CAMPAIGNS))
            ship_cls = DeltaShipper if use_delta else SnapshotShipper
            reach_ship = ship_cls(
                reach_store, list(engine.encoder.campaigns),
                interval_ms=cfg.jax_reach_ship_interval_ms,
                registry=registry,
                origin={"addr": f"{s_host}:{s_port}",
                        "pid": os.getpid(), "role": "writer"})
            engine.attach_shipper(reach_ship)
        if sampler is not None:
            # every metrics.jsonl snapshot carries the live serving
            # picture (segments/contention with query obs on, and the
            # ISSUE 14 cache/epoch/staleness block always) under
            # "reach_query" — the block `obs report/diff` renders;
            # summary() also refreshes the replica gauges each tick;
            # "ship" (ISSUE 18) is the writer's per-tick ship cost —
            # what `obs fleet` renders in the ship column
            def _reach_query_collect(rec, dt_s, srv=reach_srv,
                                     sh=reach_ship):
                rec["reach_query"] = srv.summary()
                if sh is not None:
                    rec["ship"] = sh.summary()

            sampler.add_collector(_reach_query_collect)
        r_host, r_port = reach_ps.address
        qobs = " query_obs=on" if query_obs is not None else ""
        extra = (f" cache={cfg.jax_reach_cache_capacity}"
                 if reach_cache is not None else "")
        if reach_ship is not None:
            extra += (f" ship={cfg.jax_reach_ship_dir}"
                      f"@{cfg.jax_reach_ship_interval_ms}ms"
                      f"/{reach_ship.mode}")
        print(f"reach: pubsub={r_host}:{r_port} "
              f"queue_depth={cfg.jax_reach_queue_depth} k={engine.k} "
              f"registers={engine.registers}{qobs}{extra}", flush=True)

    # everything is compiled now — engine warmup AND the reach query
    # kernel (warmed at the first state push above); any compile from
    # here on is a genuine mid-run stall
    if occupancy is not None:
        occupancy.mark_steady()

    xo = " exactly_once=on" if cfg.jax_sink_exactly_once else ""
    print(f"engine up: topic={cfg.kafka_topic} redis={cfg.redis_host}:"
          f"{cfg.redis_port} batch={engine.batch_size}{xo}", flush=True)

    if capture is not None and cfg.jax_obs_capture_oneshot:
        # config one-shot: trace the first window_s of the run (smoke
        # tests, "trace the warm ramp"); counts against the capture cap
        capture.trigger("oneshot")

    from streambench_tpu.trace import device_trace

    with device_trace(args.traceDir):
        if args.catchup:
            stats = runner.run_catchup(max_events=args.maxEvents)
        else:
            stats = runner.run(duration_s=args.duration,
                               idle_timeout_s=args.idleTimeout,
                               max_events=args.maxEvents)
    close_err: BaseException | None = None
    try:
        engine.close()
    except RuntimeError as e:
        # Rows declared lost at shutdown (the writer still held failed
        # batches after CLOSE_RETRY_LIMIT re-flushes).  The writer
        # counted them (``rows_lost`` in FaultCounters) before raising;
        # finish the accounting — stats line, flight recorder — and exit
        # non-zero instead of dying before any of it prints.
        close_err = e
        print(f"error: {e}", file=sys.stderr, flush=True)
    if deadletter is not None:
        deadletter.close()
    rows_lost = engine.faults.get("rows_lost")
    if rows_lost:
        stats.faults = dict(stats.faults, rows_lost=rows_lost)
        if flightrec is not None:
            flightrec.dump("rows_lost", terminal={
                "kind": "fault", "event": "rows_lost",
                "rows_lost": rows_lost, "error": repr(close_err)})
    # stage spans + Apex-style decile report (SURVEY.md §5.1/§5.5)
    print(engine.tracer.report(), file=sys.stderr, flush=True)
    print(engine.latency_tracker.report(), file=sys.stderr, flush=True)
    if runner.stall_detector.stalls:
        print(f"flush stalls: {runner.stall_detector.stalls}",
              file=sys.stderr, flush=True)
    stats_line = {
        "events": stats.events, "batches": stats.batches,
        "windows_written": stats.windows_written,
        "events_per_s": round(stats.events_per_s, 1),
        "dropped": engine.dropped, "wall_s": round(stats.wall_s, 2),
        "faults": stats.faults,
    }
    if getattr(broker, "counters", None) is not None:
        ksnap = {k[len("kafka_"):]: v
                 for k, v in broker.counters.snapshot().items()
                 if k.startswith("kafka_")}
        if ksnap:
            stats_line["kafka"] = ksnap
    if occupancy is not None:
        # the MEASURED busy ratio (sampled block_until_ready, not the
        # old pipelined-minus-encode estimate) + the steady-state
        # compile invariant — nonzero steady compiles is a mid-run
        # stall worth a loud line
        occ_sum = occupancy.summary()
        stats_line["device_busy_ratio"] = occ_sum["device_busy_ratio"]
        stats_line["occupancy"] = occ_sum
        steady = (occ_sum.get("compiles") or {}).get("compiles_steady")
        if steady:
            print(f"WARNING: {steady} XLA compile(s) landed after "
                  "warmup — a program shape escaped warmup or "
                  "something compiled on the hot path",
                  file=sys.stderr, flush=True)
            if flightrec is not None:
                flightrec.record("steady_compiles", count=steady)
        occupancy.close()
    if reach_srv is not None:
        # close (and drain) the query server BEFORE the SLO verdict:
        # queries answered by the drain-at-close must land in the reach
        # latency histogram the verdict judges
        reach_srv.close()
        stats_line["reach"] = reach_srv.summary()
        reach_ps.close()
        if reach_ship is not None:
            # final ship: replicas converge on the close-time planes
            reach_ship.note_state(engine.state.mins,
                                  engine.state.registers,
                                  engine.reach_epoch,
                                  int(engine.state.watermark),
                                  force=True,
                                  folded_ms=engine._fold_wall_ms)
            stats_line["reach"]["ship"] = reach_ship.summary()
            reach_store.close()
    if slo is not None:
        stats_line["slo"] = slo.verdict()
    if xfer is not None:
        # measured host->device bytes per wire format — the data-path
        # numbers the chip session needs next to the compute ratios
        stats_line["xfer"] = xfer.summary()
    if shard is not None:
        shard_sum = shard.summary()
        if shard_sum is not None:
            stats_line["shard_skew"] = shard_sum
    if devmem is not None:
        devmem.refresh_census()
        stats_line["devmem"] = devmem.summary()
    if capture is not None:
        # stop any in-flight capture (a dangling profiler drops its
        # trace at interpreter exit) and record where the evidence lives
        capture.close()
        stats_line["capture"] = capture.summary()
    if spans is not None:
        trace_path = os.path.join(args.workdir,
                                  f"trace_{os.getpid()}.json")
        spans.dump(trace_path, run=cfg.kafka_topic)
        print(f"trace: {trace_path} ({len(spans)} spans, "
              f"{spans.dropped} dropped)", file=sys.stderr, flush=True)
    if sampler is not None:
        # final telemetry record AFTER close(): the writer has drained,
        # so the record's cumulative counters and the run_stats it
        # carries agree with the JSON line below record-for-record
        sampler.close(final=stats_line)
    if metrics_server is not None:
        metrics_server.close()
    print(json.dumps(stats_line), flush=True)
    return 1 if close_err is not None else 0


if __name__ == "__main__":
    sys.exit(main())
