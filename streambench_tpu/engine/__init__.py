from streambench_tpu.engine.ingest import IngestPipeline  # noqa: F401
from streambench_tpu.engine.pipeline import AdAnalyticsEngine  # noqa: F401
from streambench_tpu.engine.runner import StreamRunner  # noqa: F401
