"""Streaming host loop: journal tail -> engine -> 1 Hz Redis flush.

The loop reproduces the operating policies of the reference engines:

- **buffer timeout** — a partial batch is dispatched once it is
  ``buffer_timeout_ms`` old (Flink's ``setBufferTimeout(100)``,
  ``AdvertisingTopologyNative.java:77-79``): latency is
  min(batch-fill-time, timeout), the same tradeoff knob.
- **1 Hz flusher** — dirty windows are written to Redis every
  ``flush_interval_ms`` (``CampaignProcessorCommon.java:41-54``).
- **pipelining** — JAX dispatch is async: while the device folds batch N,
  the host is already tailing and encoding batch N+1 (the reference gets
  this from operator threads; we get it from the runtime for free).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from streambench_tpu.checkpoint import Checkpointer
from streambench_tpu.engine.pipeline import AdAnalyticsEngine
from streambench_tpu.io.journal import JournalReader
from streambench_tpu.metrics import StallDetector
from streambench_tpu.utils.ids import now_ms


@dataclass
class RunStats:
    events: int = 0
    batches: int = 0
    flushes: int = 0
    windows_written: int = 0
    started_ms: int = 0
    finished_ms: int = 0
    # Fault/retry/recovery accounting for THIS run attempt (sink errors,
    # retries, reconnects, skipped corrupt records, DLQ lines, injected
    # chaos events...) — non-zero keys only; {} on a clean run.
    faults: dict = field(default_factory=dict)

    @property
    def wall_s(self) -> float:
        return max(self.finished_ms - self.started_ms, 1) / 1000.0

    @property
    def events_per_s(self) -> float:
        return self.events / self.wall_s


class StreamRunner:
    """Drives one engine from one journal reader until stopped."""

    # Wire bytes per event, rounded up (sizes block-mode reads; the
    # generator's JSON events run ~230 B).
    EST_EVENT_BYTES = 256

    def __init__(self, engine: AdAnalyticsEngine, reader: JournalReader,
                 batch_size: int | None = None,
                 buffer_timeout_ms: int | None = None,
                 flush_interval_ms: int | None = None,
                 checkpointer: Checkpointer | None = None,
                 checkpoint_interval_ms: int | None = None,
                 crash_points=None,
                 ingest_pipeline: str | None = None,
                 flightrec=None, spans=None):
        cfg = engine.cfg
        self.engine = engine
        self.reader = reader
        self.batch_size = batch_size or cfg.jax_batch_size
        self.buffer_timeout_ms = (buffer_timeout_ms
                                  if buffer_timeout_ms is not None
                                  else cfg.jax_buffer_timeout_ms)
        self.flush_interval_ms = (flush_interval_ms
                                  if flush_interval_ms is not None
                                  else cfg.jax_flush_interval_ms)
        self.checkpointer = checkpointer
        self.checkpoint_interval_ms = (
            checkpoint_interval_ms if checkpoint_interval_ms is not None
            else cfg.jax_checkpoint_interval_ms)
        self._last_ckpt = time.monotonic()
        # Backpressure canary: warn when the flush cadence slips to >2x its
        # period (the Apex stall warning, ProcessTimeAwareStore.java:84-87).
        # Stalls route into the engine's FaultCounters ("flush_stalls") so
        # they surface in RunStats.faults and the telemetry stream next to
        # the sink/chaos counters, not just on stderr.
        self.stall_detector = StallDetector(
            expected_period_ms=max(self.flush_interval_ms, 1),
            counters=engine.faults)
        self.stats = RunStats()
        self._stop = False
        # Chaos hook (chaos.CrashScheduler or None): ``point(kind)`` is
        # called at every batch/flush/checkpoint boundary and may raise a
        # simulated ``EngineCrash`` there — the documented crash surfaces
        # the supervised-recovery contract is verified against.  None (the
        # default) keeps the loop byte-identical to the pre-chaos runner.
        self.crash_points = crash_points
        # Staged ingest pipeline (engine.ingest): "off" keeps the serial
        # loops byte-identical, "on" forces the overlapped stages, "auto"
        # enables them where block-mode ingest makes the overlap pay.
        mode = (ingest_pipeline if ingest_pipeline is not None
                else getattr(cfg, "jax_ingest_pipeline", "off"))
        self.ingest_mode = (mode or "off").strip().lower()
        self._pipeline = None   # the live IngestPipeline during a run
        # Crash flight recorder (obs.flightrec or None): fed a "tick"
        # record at every flush cycle + checkpoint offsets, and dumped
        # with the terminal fault when a run loop dies.  None (the
        # default) costs one attribute check per flush.
        self.flightrec = flightrec
        self._flight_prev_faults: dict = {}
        # Span tracer (obs.spans or None): the engine's Tracer spans are
        # forwarded by attach_obs; the runner adds the READ side — the
        # serial loops' journal polls and the staged pipeline's stage
        # spans — so the exported timeline covers read/encode/dispatch/
        # flush/sink end to end.  None costs one attribute check per
        # poll.
        self.spans = spans

    def stop(self) -> None:
        self._stop = True

    def _chaos_point(self, kind: str) -> None:
        if self.crash_points is not None:
            self.crash_points.point(kind)

    # ------------------------------------------------------------------
    # crash flight recorder (obs.flightrec)
    def _flight_tick(self) -> None:
        """One structured sample into the flight ring (flush cadence):
        progress counters, watermark lag, sink health, fault deltas,
        and — when the staged pipeline is live — its queue depths."""
        fr = self.flightrec
        if fr is None:
            return
        tel = self.engine.telemetry()
        rec = {"events": tel["events"],
               "windows_written": tel["windows_written"],
               "watermark_lag_ms": tel["watermark_lag_ms"],
               "pending_rows": tel["pending_rows"],
               "sink_dirty_rows": tel["sink_dirty_rows"],
               "batches": self.stats.batches,
               "flushes": self.stats.flushes}
        if "sink_fence" in tel:
            rec["sink_fence"] = tel["sink_fence"]
        faults = self.engine.faults.snapshot()
        deltas = {k: v - self._flight_prev_faults.get(k, 0)
                  for k, v in faults.items()
                  if v != self._flight_prev_faults.get(k, 0)}
        self._flight_prev_faults = faults
        if deltas:
            rec["fault_deltas"] = deltas
        pipe = self._pipeline
        if pipe is not None and not pipe.closed:
            ing = pipe.telemetry()
            rec["ingest"] = {k: ing[k] for k in
                             ("block_queue_depth", "batch_queue_depth",
                              "reader_stalls", "encode_stalls")}
        fr.record("tick", **rec)

    def _flight_crash(self, err: BaseException) -> None:
        """A run loop died: freeze the ring with the terminal fault as
        the last record — the black box every chaos-sweep failure
        leaves behind instead of a bare traceback."""
        fr = self.flightrec
        if fr is None:
            return
        try:
            offset = self._reader_position()
        except Exception:
            offset = None
        fr.dump("crash", terminal={
            "kind": "fault", "event": "crash", "error": repr(err),
            "offset": offset, "events": self.stats.events,
            "batches": self.stats.batches,
            "flushes": self.stats.flushes})

    def _collect_faults(self) -> None:
        """Surface fault/retry accounting in ``stats.faults`` (end of a
        run attempt): engine counters (sink errors/retries/backoff) +
        encoder reject/DLQ counts + reader corruption/chaos counters."""
        f: dict[str, int] = dict(self.engine.faults.snapshot())

        def add(key: str, n: int) -> None:
            if n:
                f[key] = f.get(key, 0) + n

        enc = getattr(self.engine, "encoder", None)
        if enc is not None:
            add("bad_lines", int(getattr(enc, "bad_lines", 0)))
            add("dlq_lines", int(getattr(enc, "dlq_lines", 0)))
        add("journal_corrupt_skipped",
            int(getattr(self.reader, "corrupt_records", 0)))
        chaos_counts = getattr(self.reader, "fault_counters", None)
        if chaos_counts is not None:
            for k, v in chaos_counts.snapshot().items():
                add(k, v)
        self.stats.faults = f

    def _reader_position(self) -> int | list[int]:
        """Single-partition byte offset, or the per-partition offsets
        vector of a ``MultiReader`` (whose scalar ``.offset`` raises).
        With the ingest pipeline active this is the FOLDED position —
        the offset covering exactly the dispatched blocks, never the
        reader thread's read-ahead — so checkpoints and crash offsets
        (the supervisor's replay segments) stay consistent."""
        if self._pipeline is not None:
            return self._pipeline.position()
        try:
            return self.reader.offset
        except AttributeError:
            return list(self.reader.offsets)

    def resume(self) -> bool:
        """Restore engine + reader from the newest checkpoint, if any.
        Call before ``run``; returns True when a snapshot was applied."""
        if self.checkpointer is None:
            return False
        snap = self.checkpointer.load()
        if snap is None:
            return False
        self.engine.restore(snap)
        if isinstance(snap.offset, list):
            self.reader.seek_offsets(snap.offset)
        else:
            self.reader.seek(snap.offset)
        return True

    def _checkpoint_now(self, now: float) -> None:
        pipe = self._pipeline
        if pipe is not None and not pipe.closed:
            # Quiesce the stages at a work-item boundary so the snapshot
            # can serialize encoder state (base time, intern tables)
            # without racing the encode thread; the returned offset
            # covers exactly the folded blocks (in-flight prefetched
            # blocks stay replayable, never skippable).
            off = pipe.quiesce()
            try:
                self.checkpointer.save(self.engine.snapshot(off))
            finally:
                pipe.resume()
        else:
            off = self._reader_position()
            self.checkpointer.save(self.engine.snapshot(off))
        if self.flightrec is not None:
            self.flightrec.record("checkpoint", offset=off,
                                  events=self.engine.events_processed)
        self._last_ckpt = now
        self._chaos_point("checkpoint")

    def _checkpoint_due(self, now: float) -> bool:
        return (self.checkpointer is not None and
                (now - self._last_ckpt) * 1000 >= self.checkpoint_interval_ms)

    # ------------------------------------------------------------------
    # staged ingest pipeline (engine.ingest)
    def _pipeline_on(self) -> bool:
        """Resolve the ingest mode: "on" always pipelines, "auto" only
        where the overlap can actually pay — block-mode ingest (native
        encoder + a ``poll_block`` reader) AND more than one host core
        (on a single core the stages just timeslice one CPU and the
        thread handoffs are pure overhead — measured ~25% slower, see
        ``bench_ingest_pipeline.json``), "off" (default) never — the
        serial loops below stay byte-identical."""
        if self.ingest_mode == "on":
            return True
        if self.ingest_mode == "auto":
            import os

            return ((os.cpu_count() or 1) > 1
                    and getattr(self.engine, "supports_block_ingest",
                                False)
                    and hasattr(self.reader, "poll_block"))
        return False

    def _make_pipeline(self, catchup: bool):
        from streambench_tpu.engine.ingest import IngestPipeline

        cfg = self.engine.cfg
        chunk = self.batch_size * max(
            getattr(self.engine, "scan_batches", 1), 1)
        pipe = IngestPipeline(
            self.engine, self.reader,
            batch_size=self.batch_size,
            chunk_records=chunk,
            buffer_timeout_ms=self.buffer_timeout_ms,
            catchup=catchup,
            est_event_bytes=self.EST_EVENT_BYTES,
            block_queue=getattr(cfg, "jax_ingest_block_queue", 4),
            batch_queue=getattr(cfg, "jax_ingest_batch_queue", 4),
            flightrec=self.flightrec, spans=self.spans)
        self._pipeline = pipe
        return pipe

    def _fold_item(self, item) -> None:
        """Dispatch one ready group: fold in journal order, then publish
        its offset as folded (strictly after — a crash between the two
        replays the block instead of skipping it)."""
        st = self.stats
        st.events += self.engine.fold_batches(item.batches)
        st.batches += 1
        self._pipeline.commit(item)
        self._chaos_point("batch")

    def _flush_cycle(self, now: float, last_flush: float) -> float:
        """Shared 1 Hz flush + stall tick + checkpoint cadence for the
        pipelined loops.  Returns the new ``last_flush``."""
        st = self.stats
        if (now - last_flush) * 1000 >= self.flush_interval_ms:
            st.windows_written += self.engine.flush()
            st.flushes += 1
            self.stall_detector.tick(int(time.monotonic() * 1000))
            self._flight_tick()
            last_flush = now
            self._chaos_point("flush")
            if self._checkpoint_due(now):
                self._checkpoint_now(now)
        return last_flush

    def _finish_run(self) -> None:
        """Final flush + checkpoint shared by every loop's exit path."""
        st = self.stats
        st.windows_written += self.engine.flush(final=True)
        st.flushes += 1
        self._flight_tick()   # short runs still leave ring context
        self._chaos_point("flush")
        if self.checkpointer is not None:
            self._checkpoint_now(time.monotonic())

    def _run_pipelined(self, duration_s: float | None,
                       idle_timeout_s: float | None,
                       max_events: int | None) -> RunStats:
        """Streaming loop over the staged pipeline: the reader thread
        owns polling + batching (buffer_timeout semantics included), the
        encode thread owns encoding, and this loop does only device
        dispatch + flush — the stages overlap instead of taking turns."""
        from streambench_tpu.engine import ingest

        st = self.stats
        st.started_ms = now_ms()
        deadline = (time.monotonic() + duration_s) if duration_s else None
        last_flush = time.monotonic()
        pipe = self._make_pipeline(catchup=False)
        try:
            while not self._stop:
                now = time.monotonic()
                if deadline and now >= deadline:
                    break
                if max_events and st.events >= max_events:
                    break
                item = pipe.get(timeout_s=0.02)
                if item is not None and item is not ingest.EOF:
                    self._fold_item(item)
                elif (idle_timeout_s and pipe.drained()
                        and pipe.idle_for() >= idle_timeout_s):
                    # idle means the READER polled and found nothing for
                    # a while AND everything it did read was folded
                    break
                last_flush = self._flush_cycle(time.monotonic(),
                                               last_flush)
            # Drain what the stages already read (the serial loop's
            # trailing ``if pending: dispatch()``) — unless the cutoff
            # was max_events, where uncommitted blocks stay replayable.
            pipe.finish()
            drain_deadline = time.monotonic() + 10.0
            while time.monotonic() < drain_deadline:
                if max_events and st.events >= max_events:
                    break
                item = pipe.get(timeout_s=0.1)
                if item is ingest.EOF:
                    break
                if item is not None:
                    self._fold_item(item)
            self._finish_run()
        finally:
            pipe.close()
        st.finished_ms = now_ms()
        self._collect_faults()
        return st

    def _run_catchup_pipelined(self, max_events: int | None) -> RunStats:
        """Catchup over the staged pipeline: chunk-sized reads + encode
        run ahead on their threads; this loop pays only device dispatch
        and flush, so the chunk cost drops toward the device floor."""
        from streambench_tpu.engine import ingest

        st = self.stats
        st.started_ms = now_ms()
        last_flush = time.monotonic()
        pipe = self._make_pipeline(catchup=True)
        try:
            while not self._stop:
                item = pipe.get(timeout_s=0.05)
                if item is ingest.EOF:
                    break
                if item is not None:
                    self._fold_item(item)
                    if max_events and st.events >= max_events:
                        break
                last_flush = self._flush_cycle(time.monotonic(),
                                               last_flush)
            self._finish_run()
        finally:
            pipe.close()
        st.finished_ms = now_ms()
        self._collect_faults()
        return st

    def run(self, duration_s: float | None = None,
            idle_timeout_s: float | None = None,
            max_events: int | None = None) -> RunStats:
        """Consume until stopped / duration / idle-timeout / max_events.
        A loop that dies leaves its flight-recorder black box (when one
        is attached) before the exception propagates."""
        try:
            return self._run(duration_s, idle_timeout_s, max_events)
        except BaseException as e:
            self._flight_crash(e)
            raise

    def _run(self, duration_s: float | None,
             idle_timeout_s: float | None,
             max_events: int | None) -> RunStats:
        if self._pipeline_on():
            return self._run_pipelined(duration_s, idle_timeout_s,
                                       max_events)
        st = self.stats
        st.started_ms = now_ms()
        deadline = (time.monotonic() + duration_s) if duration_s else None
        last_flush = time.monotonic()
        last_data = time.monotonic()
        # Block mode (native scanner over raw bytes) when both ends
        # support it; pending then holds byte blocks, counted by newline
        # (a memchr scan, ~free) instead of per-line Python objects.
        block_mode = (getattr(self.engine, "supports_block_ingest", False)
                      and hasattr(self.reader, "poll_block"))
        est_bytes = self.EST_EVENT_BYTES
        pending: list[bytes] = []      # lines, or raw blocks in block mode
        pending_n = 0                  # records pending
        pending_since: float | None = None
        # Adaptive batching under backlog: while the reader keeps handing
        # back full reads (producer is ahead of us), grow the dispatch
        # target toward one scan-chunk so catching up pays one dispatch
        # per K batches; any short read snaps it back to one batch so
        # steady-state latency stays governed by buffer_timeout.
        chunk_cap = self.batch_size * max(
            getattr(self.engine, "scan_batches", 1), 1)
        target = self.batch_size

        def dispatch() -> None:
            nonlocal pending, pending_n, pending_since, last_data
            # count PARSED events in both modes (events_processed delta),
            # so max_events cutoffs and throughput stats don't depend on
            # which ingest mode the reader supports
            before = self.engine.events_processed
            if block_mode:
                self.engine.process_block(b"".join(pending))
            else:
                self.engine.process_chunk(pending)
            st.events += self.engine.events_processed - before
            st.batches += 1
            pending = []
            pending_n = 0
            pending_since = None
            last_data = time.monotonic()  # processing isn't idleness
            self._chaos_point("batch")

        while not self._stop:
            now = time.monotonic()
            if deadline and now >= deadline:
                break
            if max_events and st.events >= max_events:
                break

            room = target - pending_n
            full_read = False
            spans = self.spans
            t0_ns = time.perf_counter_ns() if spans is not None else 0
            if room <= 0:
                got = 0
            elif block_mode:
                budget = room * est_bytes
                data = self.reader.poll_block(budget)
                got = data.count(b"\n") if data else 0
                # records can be longer than the estimate, so judge
                # backlog by BYTES: a NON-EMPTY read that nearly filled
                # its budget means more data is waiting (an empty read
                # must never count as full, or a tiny budget at room==1
                # would busy-spin on an idle stream)
                full_read = got > 0 and len(data) >= budget - est_bytes
                if got:
                    pending.append(data)
            else:
                lines = self.reader.poll(max_records=room)
                got = len(lines)
                full_read = got >= room
                if got:
                    pending.extend(lines)
            if spans is not None and got:
                # non-empty journal reads only: empty polls at the 1 ms
                # yield cadence would flood the bounded ring
                spans.add("journal_read", t0_ns,
                          time.perf_counter_ns() - t0_ns, cat="ingest",
                          args={"records": got})
            if got:
                last_data = now
                if pending_since is None:
                    pending_since = now
                pending_n += got
                if full_read:                # backlog: scale the batch up
                    target = min(target * 2, chunk_cap)
                elif pending_n < self.batch_size:
                    target = self.batch_size
            else:
                if pending_n < self.batch_size:
                    target = self.batch_size
                if (idle_timeout_s and not pending
                        and now - last_data >= idle_timeout_s):
                    # Idle means "polled and found nothing for a while" —
                    # the clock must not tick while we were busy
                    # compiling/folding.
                    break

            batch_old = (pending_since is not None and
                         (now - pending_since) * 1000 >= self.buffer_timeout_ms)
            if pending_n >= target or (pending and batch_old):
                dispatch()
            elif not full_read:
                # Nothing due and no backlog (the read didn't fill its
                # budget): yield.  Without this the loop busy-spins once
                # the stream is fast enough that every poll returns a few
                # KB — 100% of a core burned on re-polls, starving
                # co-located producers (latency cost is bounded by
                # buffer_timeout regardless).
                time.sleep(0.001)

            if (now - last_flush) * 1000 >= self.flush_interval_ms:
                if self._checkpoint_due(now) and pending:
                    # The reader offset already covers polled-but-unprocessed
                    # lines; dispatch them first so the snapshot can't skip
                    # them on resume (and the checkpoint cadence can't be
                    # starved by a continuously non-empty buffer).
                    dispatch()
                st.windows_written += self.engine.flush()
                st.flushes += 1
                self.stall_detector.tick(int(time.monotonic() * 1000))
                self._flight_tick()
                last_flush = now
                self._chaos_point("flush")
                if self._checkpoint_due(now):
                    self._checkpoint_now(now)

        if pending:
            dispatch()
        st.windows_written += self.engine.flush(final=True)
        st.flushes += 1
        self._flight_tick()   # short runs still leave ring context
        self._chaos_point("flush")
        if self.checkpointer is not None:
            self._checkpoint_now(time.monotonic())
        st.finished_ms = now_ms()
        self._collect_faults()
        return st

    def run_catchup(self, max_events: int | None = None) -> RunStats:
        """Drain the journal as fast as possible (catchup/throughput mode):
        scan-chunked batches, no buffer timeout, flush only on ring-span
        guard + once per second of wall clock."""
        try:
            return self._run_catchup(max_events)
        except BaseException as e:
            self._flight_crash(e)
            raise

    def _run_catchup(self, max_events: int | None) -> RunStats:
        if self._pipeline_on():
            return self._run_catchup_pipelined(max_events)
        st = self.stats
        st.started_ms = now_ms()
        last_flush = time.monotonic()
        chunk = self.batch_size * getattr(self.engine, "scan_batches", 1)
        # Block-mode ingest (native encoder scans raw bytes; no per-line
        # Python objects) when both ends support it; MultiReader and the
        # Kafka adapter stay on the line path.
        block_mode = (getattr(self.engine, "supports_block_ingest", False)
                      and hasattr(self.reader, "poll_block"))
        block_bytes = chunk * self.EST_EVENT_BYTES
        spans = self.spans
        while not self._stop:
            before = self.engine.events_processed
            t0_ns = time.perf_counter_ns() if spans is not None else 0
            if block_mode:
                data = self.reader.poll_block(block_bytes)
                if not data:
                    break
                if spans is not None:
                    spans.add("journal_read", t0_ns,
                              time.perf_counter_ns() - t0_ns,
                              cat="ingest")
                self.engine.process_block(data)
            else:
                lines = self.reader.poll(max_records=chunk)
                if not lines:
                    break
                if spans is not None:
                    spans.add("journal_read", t0_ns,
                              time.perf_counter_ns() - t0_ns,
                              cat="ingest", args={"records": len(lines)})
                self.engine.process_chunk(lines)
            st.events += self.engine.events_processed - before
            st.batches += 1
            self._chaos_point("batch")
            if max_events and st.events >= max_events:
                break
            now = time.monotonic()
            if (now - last_flush) * 1000 >= self.flush_interval_ms:
                st.windows_written += self.engine.flush()
                st.flushes += 1
                self.stall_detector.tick(int(time.monotonic() * 1000))
                self._flight_tick()
                last_flush = now
                self._chaos_point("flush")
                if self._checkpoint_due(now):
                    self._checkpoint_now(now)
        st.windows_written += self.engine.flush(final=True)
        st.flushes += 1
        self._flight_tick()   # short runs still leave ring context
        self._chaos_point("flush")
        if self.checkpointer is not None:
            self._checkpoint_now(time.monotonic())
        st.finished_ms = now_ms()
        self._collect_faults()
        return st
