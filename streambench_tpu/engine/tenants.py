"""Multi-tenant host: N topologies in one process on one device.

The reference harness runs one workload per engine process; the
"millions of users" north star is the opposite shape — several
topologies (an exact windowed count, a session CMS, a reach serving
tier) sharing one process and one accelerator.  This module is the
host that makes that shape *observable and governable* (obs layer 9):

- every tenant gets its own engine, its own fakeredis sink, its own
  :class:`~streambench_tpu.obs.tenancy.TenantRegistry` view over the
  ONE shared registry (all its instruments carry ``tenant=``), its own
  :class:`~streambench_tpu.obs.occupancy.OccupancySampler` whose
  sampled busy windows feed the shared
  :class:`~streambench_tpu.obs.tenancy.DeviceTimeLedger`, and (when an
  objective is declared) its own per-tenant
  :class:`~streambench_tpu.obs.slo.SloTracker`;
- one shared :class:`~streambench_tpu.obs.sampler.MetricsSampler`
  journals everything into one ``metrics.jsonl``: per-tenant blocks
  under ``rec["tenants"][name]``, per-tenant SLO under
  ``rec["slo_tenants"][name]``, the blame matrix + partition check
  under ``rec["multitenant"]``, and admission-controller state under
  ``rec["admission"]``;
- ingest is a bounded per-tenant batch queue.  Batches stamp their
  enqueue time; the fold loop records enqueue→fold as the tenant's
  measured *wait* (the blame matrix's victim side).  A reach tenant's
  waits come from its server's admit→pop pairs instead.
- when ``jax.admission.enabled`` is set the host consults the
  :class:`~streambench_tpu.obs.admission.AdmissionController` before
  folding: a defer gate leaves the aggressor's batches queued (nothing
  lost), a shed gate drops its oldest batch (counted per tenant).
  Default-off: without the flag the fold loop never calls into
  admission at all.
- a defer gate also *actuates upstream* when the tenant's reader
  supports it (the Kafka adapter's ``pause()``/``resume()``): the
  paused consumer stops fetching, so the aggressor's backlog
  accumulates IN THE BROKER — measured by the per-tenant
  ``streambench_kafka_consumer_lag`` gauge — instead of ballooning the
  host queue.  Release (or escalation to shed) resumes the consumer.
  Readers without ``pause`` (FileBroker) just keep the old
  queue-backlog behavior.

Round-robin fairness note, stated honestly: on one CPU core the
"device" and the host loop share the core, so a flash crowd on one
tenant delays everyone through the GIL *and* the device queue — which
is exactly the interference the blame matrix measures.
"""

from __future__ import annotations

import time
from collections import deque

from streambench_tpu.io.fakeredis import make_store
from streambench_tpu.io.redis_schema import as_redis
from streambench_tpu.obs import (
    AdmissionController,
    DeviceTimeLedger,
    OccupancySampler,
    SloTracker,
    TenantRegistry,
    engine_collector,
)

#: engine kinds a tenant can declare (the engine CLI's families)
TENANT_KINDS = ("exact", "hll", "sliding", "session", "reach", "hllx")

#: per-tenant ingest queue bound: a deferred tenant's backlog is
#: bounded — past it the OLDEST batch is dropped and counted, the
#: shed-not-wedge rule every bounded queue in the repo follows
QUEUE_MAX = 1024


def parse_tenants(spec: str) -> list[dict]:
    """``"alpha:exact,beta:session,gamma:reach"`` -> tenant dicts.

    Names must be unique and non-empty; a missing kind defaults to
    ``exact``.  The spec grammar is deliberately the fleet
    ``parse_role_spec`` shape — one flat comma list, loud errors.
    """
    out: list[dict] = []
    seen: set = set()
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, kind = part.partition(":")
        name = name.strip()
        kind = (kind.strip() or "exact")
        if not name:
            raise ValueError(f"tenant with empty name in {spec!r}")
        if name in seen:
            raise ValueError(f"duplicate tenant {name!r} in {spec!r}")
        if kind not in TENANT_KINDS:
            raise ValueError(
                f"tenant {name!r} declares unknown kind {kind!r} "
                f"(supported: {', '.join(TENANT_KINDS)})")
        seen.add(name)
        out.append({"name": name, "kind": kind})
    if not out:
        raise ValueError(f"no tenants in spec {spec!r}")
    return out


class _Tenant:
    """One tenant's runtime bundle (host-internal)."""

    __slots__ = ("name", "kind", "engine", "view", "occupancy", "slo",
                 "queue", "reader", "serve", "folded_batches",
                 "dropped_batches", "wait_seen")

    def __init__(self, name, kind):
        self.name = name
        self.kind = kind
        self.engine = None
        self.view = None
        self.occupancy = None
        self.slo = None
        self.queue: deque = deque()
        self.reader = None
        self.serve = None
        self.folded_batches = 0
        self.dropped_batches = 0
        # high-water (pop_ns, admit_ns) over the serve wait ring:
        # wait_intervals() returns the WHOLE bounded ring each call, so
        # the drain must consume only what it has not seen yet or every
        # drain re-attributes the same waits (ring order is pop order,
        # admits ascending within one pop batch — lexicographic works)
        self.wait_seen = (0, 0)


class MultiTenantHost:
    """Build, feed, meter and (optionally) govern N tenant engines.

    ``specs`` is :func:`parse_tenants` output, optionally extended per
    tenant with objective keys (``p99_ms``, ``rate_evps``,
    ``reach_p99_ms``) and ``serve=True`` for a reach tenant that
    should answer live queries.  ``registry`` is the ONE shared
    :class:`MetricsRegistry`; ``sampler`` (optional) is the shared
    MetricsSampler the host adds its collectors to.
    """

    def __init__(self, cfg, specs, mapping, campaigns=None, *,
                 registry, sampler=None, sample_every: int = 4,
                 admission: bool = False,
                 admission_kw: "dict | None" = None,
                 queue_max: int = QUEUE_MAX,
                 redis_factory=None,
                 clock=time.monotonic):
        self.cfg = cfg
        self.mapping = mapping
        self.campaigns = campaigns
        self.registry = registry
        self.sampler = sampler
        self.sample_every = max(int(sample_every), 1)
        self.queue_max = max(int(queue_max), 1)
        # called once per tenant; default is a private in-process store
        # per tenant (the CLI passes a factory honoring cfg.redis_host
        # so harness evidence walks see the windows)
        self._redis_factory = redis_factory
        self._clock = clock
        self.ledger = DeviceTimeLedger(registry=registry)
        self._tenants: "dict[str, _Tenant]" = {}
        for spec in specs:
            self._build(dict(spec))
        self.admission = None
        if admission:
            self.admission = AdmissionController(
                self.ledger, self._burns, registry=registry,
                sampler=sampler, lags=self.reader_lags,
                **(admission_kw or {}))
        if sampler is not None:
            sampler.add_collector(self._host_collector())

    # -- construction --------------------------------------------------
    def _build(self, spec: dict) -> None:
        name = spec["name"]
        kind = spec.get("kind", "exact")
        t = _Tenant(name, kind)
        t.view = TenantRegistry(self.registry, name)
        self.ledger.declare(name)
        redis = (self._redis_factory() if self._redis_factory is not None
                 else as_redis(make_store()))
        if kind == "exact":
            from streambench_tpu.engine.pipeline import AdAnalyticsEngine

            t.engine = AdAnalyticsEngine(
                self.cfg, self.mapping, campaigns=self.campaigns,
                redis=redis)
        else:
            from streambench_tpu.engine.sketches import (
                HLLDistinctEngine,
                HLLXEngine,
                ReachSketchEngine,
                SessionCMSEngine,
                SlidingTDigestEngine,
            )

            cls = {"hll": HLLDistinctEngine,
                   "sliding": SlidingTDigestEngine,
                   "session": SessionCMSEngine,
                   "reach": ReachSketchEngine,
                   "hllx": HLLXEngine}[kind]
            t.engine = cls(self.cfg, self.mapping,
                           campaigns=self.campaigns, redis=redis)
        t.occupancy = OccupancySampler(t.view,
                                       sample_every=self.sample_every,
                                       watch_compiles=False)
        t.occupancy.busy_sink = self.ledger.busy_sink(name)
        t.engine.attach_obs(t.view, occupancy=t.occupancy)
        p99 = int(spec.get("p99_ms") or 0)
        rate = int(spec.get("rate_evps") or 0)
        reach_p99 = int(spec.get("reach_p99_ms") or 0)
        if p99 or rate or reach_p99:
            t.slo = SloTracker(
                t.view, p99_ms=p99, rate_evps=rate,
                reach_p99_ms=reach_p99,
                budget=float(getattr(self.cfg, "jax_slo_budget", 0.01)),
                fast_s=float(spec.get(
                    "fast_s", getattr(self.cfg, "jax_slo_fast_s", 30))),
                slow_s=float(spec.get(
                    "slow_s", getattr(self.cfg, "jax_slo_slow_s", 180))),
                tenant=name,
                annotate=(self.sampler.annotate
                          if self.sampler is not None else None))
        if kind == "reach" and spec.get("serve"):
            from streambench_tpu.reach.serve import ReachQueryServer

            t.serve = ReachQueryServer(
                self.campaigns or [], registry=t.view,
                hold=bool(spec.get("serve_hold", False)))
            t.engine.attach_reach(t.serve)
        if self.sampler is not None:
            self.sampler.add_collector(self._tenant_collector(t))
        self._tenants[name] = t

    # -- journal plumbing ----------------------------------------------
    def _tenant_collector(self, t: _Tenant):
        inner = engine_collector(t.engine, registry=t.view)

        def collect(rec: dict, dt_s: float) -> None:
            sub: dict = {"kind": t.kind}
            inner(sub, dt_s)
            sub["queued_batches"] = len(t.queue)
            sub["folded_batches"] = t.folded_batches
            sub["dropped_batches"] = t.dropped_batches
            lag_fn = getattr(t.reader, "lag", None)
            if lag_fn is not None:
                try:
                    lag = int(lag_fn())
                except Exception:
                    lag = None
                if lag is not None:
                    sub["consumer_lag"] = lag
                    sub["reader_paused"] = bool(
                        getattr(t.reader, "paused", False))
                    t.view.gauge(
                        "streambench_kafka_consumer_lag",
                        "broker log end minus this consumer's position"
                        " (records not yet fetched)").set(lag)
            if t.serve is not None:
                sub["reach_query"] = t.serve.summary()
            if t.slo is not None:
                # the tenant-scoped tracker journals into the
                # RECORD-level slo_tenants map, not the tenant block —
                # hoist it up where diagnose() reads it
                t.slo.collect(sub, dt_s)
                st = sub.pop("slo_tenants", None)
                if st:
                    rec.setdefault("slo_tenants", {}).update(st)
            rec.setdefault("tenants", {})[t.name] = sub

        return collect

    def _host_collector(self):
        def collect(rec: dict, dt_s: float) -> None:
            self.drain_waits()
            mt = self.ledger.summary()
            mt["partition"] = self.partition_check()
            rec["multitenant"] = mt
            if self.admission is not None:
                rec["admission"] = self.admission.summary()

        return collect

    def _burns(self) -> dict:
        return {t.name: t.slo.fast_burn()
                for t in self._tenants.values() if t.slo is not None}

    def reader_lags(self) -> dict:
        """``{tenant: broker-side consumer lag}`` for every tenant
        whose reader can measure it (the Kafka adapter's ``lag()``).
        The admission controller journals this map with every gate
        decision — the broker-backlog evidence the defer actuator is
        judged by."""
        out: dict = {}
        for t in self._tenants.values():
            lag_fn = getattr(t.reader, "lag", None)
            if lag_fn is None:
                continue
            try:
                out[t.name] = int(lag_fn())
            except Exception:
                pass
        return out

    # -- ingest --------------------------------------------------------
    def tenants(self) -> list[str]:
        return list(self._tenants)

    def tenant(self, name: str) -> _Tenant:
        return self._tenants[name]

    def offer(self, name: str, lines: list) -> None:
        """Queue one ingest batch for a tenant (enqueue-stamped for
        wait attribution).  A full queue drops the OLDEST batch."""
        t = self._tenants[name]
        if len(t.queue) >= self.queue_max:
            t.queue.popleft()
            t.dropped_batches += 1
        t.queue.append((time.perf_counter_ns(), lines))

    def pump(self, max_records: int = 4096) -> int:
        """Poll each tenant's journal reader (when wired) into its
        queue.  Returns total lines moved."""
        moved = 0
        for t in self._tenants.values():
            if t.reader is None or getattr(t.reader, "paused", False):
                continue
            lines = t.reader.poll(max_records)
            if lines:
                self.offer(t.name, lines)
                moved += len(lines)
        return moved

    def _sync_reader_gates(self) -> None:
        """Mirror admission gates onto pausable readers: a defer gate
        pauses the tenant's consumer (backlog accumulates broker-side,
        not in the host queue); anything else — admit, release, or a
        shed escalation (which must keep consuming to keep shedding) —
        resumes it."""
        for t in self._tenants.values():
            r = t.reader
            if r is None or not hasattr(r, "pause"):
                continue
            want = self.admission.admit(t.name) == "defer"
            if want and not getattr(r, "paused", False):
                r.pause()
            elif not want and getattr(r, "paused", False):
                r.resume()

    def step(self) -> int:
        """One round-robin fold pass: at most one queued batch per
        tenant, admission-gated.  Returns batches folded."""
        folded = 0
        if self.admission is not None:
            self._sync_reader_gates()
        for t in self._tenants.values():
            if not t.queue:
                continue
            if self.admission is not None:
                action = self.admission.admit(t.name)
                if action == "defer":
                    self.admission.note_deferred(t.name)
                    continue
                if action == "shed":
                    t.queue.popleft()
                    self.admission.note_shed(t.name)
                    continue
            t_enq, lines = t.queue.popleft()
            self.ledger.note_wait(t.name, t_enq,
                                  time.perf_counter_ns())
            t.engine.process_lines(lines)
            t.folded_batches += 1
            folded += 1
        return folded

    def drain_waits(self) -> None:
        """Pull reach servers' admit→pop wait pairs into the ledger
        (the serving tenant's victim-side evidence)."""
        for t in self._tenants.values():
            if t.serve is not None:
                seen = t.wait_seen
                for a_ns, p_ns in t.serve.wait_intervals():
                    if (p_ns, a_ns) <= seen:
                        continue
                    self.ledger.note_wait(t.name, a_ns, p_ns)
                    if (p_ns, a_ns) > t.wait_seen:
                        t.wait_seen = (p_ns, a_ns)

    def control_step(self) -> "dict | None":
        """One admission-controller pass (no-op when admission is
        off)."""
        if self.admission is None:
            return None
        self.drain_waits()
        return self.admission.step()

    def flush_all(self, final: bool = False) -> None:
        for t in self._tenants.values():
            t.engine.flush(final=final)

    def warmup(self) -> None:
        for t in self._tenants.values():
            t.engine.warmup()

    # -- invariants + reporting ----------------------------------------
    def partition_check(self) -> dict:
        """The blame matrix's conservation law over the LIVE samplers:
        per-tenant attributed busy must sum to the occupancy samplers'
        measured busy."""
        return self.ledger.partition_check(
            {t.name: t.occupancy.busy_ns
             for t in self._tenants.values()})

    def summary(self) -> dict:
        out: dict = {"tenants": {}}
        for t in self._tenants.values():
            tel = t.engine.telemetry()
            block = {
                "kind": t.kind,
                "events": tel["events"],
                "windows_written": tel["windows_written"],
                "folded_batches": t.folded_batches,
                "queued_batches": len(t.queue),
                "dropped_batches": t.dropped_batches,
                "occupancy": t.occupancy.summary(),
            }
            if t.slo is not None:
                block["slo"] = t.slo.verdict()
            if t.serve is not None:
                block["reach_query"] = t.serve.summary()
            out["tenants"][t.name] = block
        mt = self.ledger.summary()
        mt["partition"] = self.partition_check()
        out["multitenant"] = mt
        if self.admission is not None:
            out["admission"] = self.admission.summary()
        return out

    def total_events(self) -> int:
        return sum(t.engine.telemetry()["events"]
                   for t in self._tenants.values())

    def close(self, final: bool = True) -> dict:
        """Final flush + close every tenant (runner ordering: flush
        ``final=True`` BEFORE close); returns the final summary."""
        self.drain_waits()
        for t in self._tenants.values():
            if t.serve is not None:
                t.serve.close()
            try:
                t.engine.flush(final=final)
            except Exception:
                pass
        out = self.summary()
        for t in self._tenants.values():
            t.engine.close()
            t.occupancy.close()
        return out


def run_tenants_cli(cfg, args, mapping, campaigns) -> int:
    """The engine CLI's ``--tenants`` branch: run the multi-tenant
    host over the shared broker topic until ``--duration`` /
    ``--maxEvents`` / catch-up drain, then print one stats line.

    Every tenant tails the SAME topic with its OWN reader (the shared
    firehose feeds N disjoint topologies — the many-users shape), so
    offsets never contend and a deferred tenant's backlog is visible
    as its reader/queue lag, not anyone else's.
    """
    import json
    import os
    import signal

    from streambench_tpu.io.kafka import make_broker
    from streambench_tpu.obs import (
        MetricsRegistry,
        MetricsSampler,
        MetricsServer,
    )

    specs = parse_tenants(getattr(args, "tenants", None)
                          or cfg.jax_tenants)
    for s in specs:
        if s["kind"] == "reach":
            s["serve"] = True
            if cfg.jax_reach_slo_p99_ms:
                s["reach_p99_ms"] = cfg.jax_reach_slo_p99_ms
        else:
            if cfg.jax_slo_p99_ms:
                s["p99_ms"] = cfg.jax_slo_p99_ms
            if cfg.jax_slo_rate_evps:
                s["rate_evps"] = cfg.jax_slo_rate_evps

    broker = make_broker(cfg.kafka_bootstrap_servers,
                         args.brokerDir
                         or os.path.join(args.workdir, "broker"),
                         fake=cfg.kafka_fake)
    broker.create_topic(cfg.kafka_topic)
    registry = MetricsRegistry()
    sampler = None
    if cfg.jax_metrics_interval_ms > 0:
        sampler = MetricsSampler(
            os.path.join(args.workdir, "metrics.jsonl"),
            interval_ms=cfg.jax_metrics_interval_ms,
            registry=registry, role="host")
    def _make_redis():
        if cfg.redis_host == ":inprocess:":
            return as_redis(make_store())
        from streambench_tpu.io.resp import RespClient

        return RespClient(cfg.redis_host, cfg.redis_port)

    host = MultiTenantHost(
        cfg, specs, mapping, campaigns=campaigns, registry=registry,
        sampler=sampler, redis_factory=_make_redis,
        admission=cfg.jax_admission_enabled,
        admission_kw={
            "breach_ticks": cfg.jax_admission_breach_ticks,
            "healthy_ticks": cfg.jax_admission_healthy_ticks,
            "escalate_ticks": cfg.jax_admission_escalate_ticks,
            "cooldown_s": cfg.jax_admission_cooldown_s,
        })
    for name in host.tenants():
        host.tenant(name).reader = broker.reader(cfg.kafka_topic)
    if (sampler is not None
            and getattr(broker, "counters", None) is not None):
        from streambench_tpu.obs import kafka_collector

        # one broker-level ledger block per tick (the per-tenant lag
        # gauges live in each tenant's collector); host-level lag is
        # the WORST tenant's — the admission actuator's headline
        sampler.add_collector(kafka_collector(
            broker.counters,
            lag=lambda: max(host.reader_lags().values(), default=0),
            registry=registry))
    host.warmup()
    if sampler is not None:
        sampler.start()
    server = None
    if cfg.jax_metrics_port >= 0:
        refresh = sampler.collect_now if sampler is not None else None
        server = MetricsServer(registry, port=cfg.jax_metrics_port,
                               refresh=refresh)
    print(f"tenants up: {','.join(host.tenants())}"
          + (f" (admission on)" if host.admission else ""),
          flush=True)

    # the harness stops engines with SIGTERM (stream_bench
    # stop_if_needed) — convert it into a clean drain so the stats
    # line and the final journal flush still happen
    stopping = []
    try:
        signal.signal(signal.SIGTERM, lambda *_: stopping.append(1))
    except ValueError:  # not the main thread (in-process embedding)
        pass

    t0 = time.monotonic()
    deadline = (t0 + args.duration) if args.duration else None
    flush_s = max(cfg.jax_flush_interval_ms, 1) / 1000.0
    last_flush = last_ctrl = t0
    idle_since = None
    try:
        while True:
            now = time.monotonic()
            if stopping:
                break
            if deadline is not None and now >= deadline:
                break
            if (args.maxEvents
                    and host.total_events() >= args.maxEvents):
                break
            moved = host.pump()
            folded = host.step()
            if host.admission is not None and now - last_ctrl >= 0.5:
                host.control_step()
                last_ctrl = now
            if now - last_flush >= flush_s:
                host.flush_all()
                last_flush = now
            if moved or folded:
                idle_since = None
                continue
            host.drain_waits()
            if args.catchup:
                break
            if args.idleTimeout is not None:
                if idle_since is None:
                    idle_since = now
                elif now - idle_since >= args.idleTimeout:
                    break
            time.sleep(0.005)
    except KeyboardInterrupt:
        pass
    summary = host.close()
    stats_line = {
        "engine": "multitenant",
        "tenants": {name: {
            "kind": b["kind"], "events": b["events"],
            "windows_written": b["windows_written"],
            "folded_batches": b["folded_batches"],
            **({"slo_pass": b["slo"]["pass"]} if "slo" in b else {}),
        } for name, b in summary["tenants"].items()},
        "events": sum(b["events"]
                      for b in summary["tenants"].values()),
        "blame_offdiag_ratio":
            summary["multitenant"]["offdiag_ratio"],
        "partition_ok": summary["multitenant"]["partition"]["ok"],
    }
    if "admission" in summary:
        adm = summary["admission"]
        stats_line["admission"] = {
            k: adm[k] for k in ("defers", "sheds", "releases", "holds",
                                "batches_deferred", "batches_shed")}
    if getattr(broker, "counters", None) is not None:
        ksnap = {k[len("kafka_"):]: v
                 for k, v in broker.counters.snapshot().items()
                 if k.startswith("kafka_")}
        if ksnap:
            stats_line["kafka"] = ksnap
    print(json.dumps(stats_line), flush=True)
    if server is not None:
        server.close()
    if sampler is not None:
        sampler.close(final=stats_line)
    return 0
