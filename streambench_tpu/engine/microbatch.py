"""Count-based micro-batch mode: the fork's barrier-aligned windows.

The reference fork's research vehicle (``MockWindowedFlatMap``,
``AdvertisingTopologyNative.java:167-254``) replaces event-time windows
with *count-based* ones: each of ``map.partitions`` parallel mappers
buffers ``window.size / map.partitions`` events, then all partitions
rendezvous at a window barrier; the last partition to arrive becomes the
owner and stamps the window's start time into Redis, the rest spin on
``HGET start_time``.  Every event is tagged with that shared stamp, and the
downstream processor records per-window latency ``now − start`` which it
dumps to a Redis hash at job close (``CampaignProcessor``, ``:477-533``).

This module re-expresses that design TPU-first:

- the per-window work (filter "view" -> join -> per-campaign count) is one
  jitted segment-sum over the whole window — a micro-batch IS a window, so
  the keyed shuffle collapses to a single ``[C]`` count vector per
  partition, merged across partitions by addition (the host analog of the
  ``psum`` merge; the network shuffle never happens);
- in-process partitions align on a ``threading.Barrier`` whose action
  stamps the window (``LocalWindowBarrier``) — the device-step-alignment
  analog; distributed processes use ``RedisWindowBarrier``, the fork's
  protocol with one fix: the fork HDELs a *shared* ``start_time`` field on
  window entry, which lets a late-arriving partition delete the stamp the
  owner just wrote (a real race in the reference, SURVEY.md §5.2); here
  stamps are per-window-index fields ``start_time:<k>``, so nothing is
  ever deleted while being waited on;
- the latency dump keeps the fork's exact hash schema
  (``redis.hashtable``: ``thread_idx``, ``running_time:<i>``,
  ``<windowStart>:<i>`` -> latency) via ``dump_latency_hash``.

Unlike the fork (where every parallel source re-reads the *same* events
file, ``FileBasedDataSource`` x ``map.partitions``), partitions here each
consume their own broker partition — real data parallelism.
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.encode.native_encoder import make_encoder
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import RedisLike, dump_latency_hash
from streambench_tpu.utils.ids import now_ms


# ----------------------------------------------------------------------
# window barriers
# ----------------------------------------------------------------------

class LocalWindowBarrier:
    """In-process rendezvous: the barrier action stamps the window start.

    The action runs exactly once per generation before any waiter is
    released, so every partition reads the same stamp — the same role the
    fork's "last HINCRBY arrival" owner plays.
    """

    def __init__(self, n_partitions: int, timeout_s: float = 60.0):
        self._stamps: dict[int, int] = {}
        self._timeout = timeout_s
        self.ended = False  # abort() was an end-of-stream, not a timeout
        self._barrier = threading.Barrier(n_partitions, action=self._stamp)

    def _stamp(self) -> None:
        # generations are sequential: all partitions are at window k here
        self._stamps[len(self._stamps)] = now_ms()

    def arrive(self, window_idx: int) -> int:
        try:
            self._barrier.wait(self._timeout)
        except threading.BrokenBarrierError:
            # CPython Barrier race: a peer that already passed this
            # generation can abort() (end-of-stream) before WE re-check
            # the barrier state on wake-up, poisoning a generation that
            # in fact completed.  The stamp discriminates exactly: the
            # barrier action ran (stamp exists) iff our generation
            # completed — return it; only a genuinely un-assembled
            # window falls through.
            if window_idx in self._stamps:
                return self._stamps[window_idx]
            if self.ended:
                raise  # normal end-of-stream release (drive() swallows it)
            # Barrier.wait's own timeout also breaks the barrier; surface
            # it as the error it is instead of a silent partial result.
            raise TimeoutError(
                f"window barrier {window_idx}: a partition failed to "
                f"arrive within {self._timeout}s") from None
        return self._stamps[window_idx]

    def abort(self) -> None:
        """End the run: once any partition hits end-of-stream no further
        window can ever assemble (the barrier needs all parties), so
        waiting peers are released with ``BrokenBarrierError`` and their
        in-flight window is dropped — consistent with the no-partial-
        windows rule."""
        self.ended = True
        self._barrier.abort()

    def reset(self) -> None:
        """No-op: a fresh object IS a fresh barrier (state is in-process)."""


class RedisWindowBarrier:
    """The fork's Redis barrier, with per-window stamp keys (see module
    docstring for the delete-race fix).  Protocol per window ``k``:

    - ``HINCRBY <table> partition_count 1``; the arrival that brings the
      count to ``n_partitions`` resets it to 0 and becomes the owner
      (``start_new_window``, ``AdvertisingTopologyNative.java:228-238``);
    - owner: ``HSET <table> start_time:<k> now`` (``finish_window``);
    - others: 1 ms-sleep spin on ``HGET start_time:<k>`` (``wait_window``).

    Construction is **side-effect-free**: residue from a prior run
    (``partition_count`` left by an aborted run's already-arrived spinners,
    a stale ``aborted`` broadcast) is cleared by ``reset()``, which the run
    *driver* calls exactly once before any partition starts — a
    per-partition constructor clear would itself race with peers already
    arriving, and can erase a live run's end-of-stream broadcast.  (The
    fork has both flaws and leans on the harness FLUSHALL between runs.)
    Runs sharing one hashtable can alternatively be isolated with
    ``run_id``, which namespaces every barrier field.
    """

    def __init__(self, redis: RedisLike, hashtable: str, n_partitions: int,
                 poll_interval_s: float = 0.001, timeout_s: float = 60.0,
                 run_id: str = ""):
        self.redis = redis
        self.table = hashtable
        self.n = n_partitions
        self._poll = poll_interval_s
        self._timeout = timeout_s
        suffix = f":{run_id}" if run_id else ""
        self._f_count = "partition_count" + suffix
        self._f_abort = "aborted" + suffix
        self._f_stamp = "start_time" + suffix

    def reset(self) -> None:
        """Clear this run's barrier fields.  MUST be called exactly once,
        by the driver, before any partition can arrive.

        Clears the per-window stamps too: a stale ``start_time:<k>`` from
        a prior run would satisfy a spinner *instantly* — partitions would
        stop rendezvousing at all and every event would carry the previous
        run's stamp (garbage latencies)."""
        self.redis.execute("HDEL", self.table, self._f_count)
        self.redis.execute("HDEL", self.table, self._f_abort)
        prefix = self._f_stamp + ":"
        flat = (self.redis.hgetall(self.table)
                if hasattr(self.redis, "hgetall") else {})
        for name in flat:
            if name.startswith(prefix):
                self.redis.execute("HDEL", self.table, name)

    def arrive(self, window_idx: int) -> int:
        if self.redis.execute("HGET", self.table, self._f_abort) is not None:
            raise threading.BrokenBarrierError
        my = int(self.redis.execute("HINCRBY", self.table, self._f_count, 1))
        field_ = f"{self._f_stamp}:{window_idx}"
        if my == self.n:
            self.redis.execute("HSET", self.table, self._f_count, "0")
            stamp = now_ms()
            self.redis.execute("HSET", self.table, field_, str(stamp))
            return stamp
        deadline = time.monotonic() + self._timeout
        while True:
            res, ab = self.redis.pipeline_execute(
                [("HGET", self.table, field_),
                 ("HGET", self.table, self._f_abort)])
            if res is not None:
                return int(res)
            if ab is not None:
                # a peer hit end-of-stream: this window can never assemble
                raise threading.BrokenBarrierError
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"window barrier {window_idx}: no stamp after "
                    f"{self._timeout}s (partition died?)")
            time.sleep(self._poll)

    def abort(self) -> None:
        """End-of-stream broadcast: release peers parked in ``arrive``
        (their in-flight window is dropped, matching the local barrier)."""
        self.redis.execute("HSET", self.table, self._f_abort, "1")


# ----------------------------------------------------------------------
# the per-window device kernel
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_campaigns", "view_type"))
def window_campaign_counts(join_table, ad_idx, event_type, valid,
                           *, num_campaigns: int, view_type: int = 0):
    """One micro-batch window -> per-campaign view counts ``[C]``.

    The whole fork chain (EventFilterBolt -> project -> RedisJoinBolt ->
    keyBy(campaign) -> count) as a single masked segment-sum: the keyed
    shuffle is just a scatter-add index.
    """
    campaign = join_table[ad_idx]
    mask = valid & (event_type == view_type) & (campaign >= 0)
    idx = jnp.where(mask, campaign, num_campaigns)  # OOB rows dropped
    return jnp.zeros((num_campaigns,), jnp.int32).at[idx].add(1, mode="drop")


# ----------------------------------------------------------------------
# per-partition mapper + multi-partition driver
# ----------------------------------------------------------------------

@dataclass
class PartitionResult:
    partition: int
    windows: int = 0
    events: int = 0
    started_ms: int = 0    # first window start stamp
    finished_ms: int = 0   # completion time of the last window
    # window index -> per-campaign counts [C].  Indexed by window ordinal,
    # not stamp: in catchup runs consecutive windows can share a
    # millisecond and stamp-keyed state would silently merge them (the
    # fork has exactly this hazard — its latency map is stamp-keyed).
    counts: dict[int, np.ndarray] = field(default_factory=dict)
    # window index -> barrier stamp (shared across partitions)
    stamps: dict[int, int] = field(default_factory=dict)
    # window start stamp -> last observed latency (now - start), fork style
    latency: dict[int, int] = field(default_factory=dict)

    @property
    def running_time_ms(self) -> int:
        return max(self.finished_ms - self.started_ms, 0)


class MicroBatchMapper:
    """One map partition: buffer ``partition_size`` lines, rendezvous,
    fold the window on device, record latency."""

    def __init__(self, cfg: BenchmarkConfig, encoder, join_table_dev,
                 barrier, partition: int, input_format: str = "json"):
        if cfg.window_size % cfg.map_partitions:
            raise ValueError(
                f"window.size {cfg.window_size} not divisible by "
                f"map.partitions {cfg.map_partitions}")
        self.partition_size = cfg.window_size // cfg.map_partitions
        self.encoder = encoder
        self.join_table_dev = join_table_dev
        self.barrier = barrier
        # "json" for generator journals; "tbl" for the fork's pipe-separated
        # events files (AdvertisingTopologyNative.java:210: "u|p|ad|...")
        self._encode = (encoder.encode if input_format == "json"
                        else encoder.encode_tbl)
        self.result = PartitionResult(partition)
        self._buf: list[bytes] = []
        self._window_idx = 0

    def feed(self, lines: list[bytes]) -> None:
        for line in lines:
            self._buf.append(line)
            if len(self._buf) == self.partition_size:
                self._close_window()

    def _close_window(self) -> None:
        start = self.barrier.arrive(self._window_idx)
        batch = self._encode(self._buf, self.partition_size)
        counts = np.asarray(window_campaign_counts(
            self.join_table_dev, batch.ad_idx, batch.event_type,
            batch.valid, num_campaigns=self.encoder.num_campaigns))
        r = self.result
        r.counts[self._window_idx] = counts
        r.stamps[self._window_idx] = start
        done = now_ms()
        r.latency[start] = done - start
        if not r.started_ms:
            r.started_ms = start
        r.finished_ms = done
        r.windows += 1
        r.events += len(self._buf)
        self._buf.clear()
        self._window_idx += 1

    @property
    def leftover(self) -> int:
        """Events short of a full window at end of stream (the fork simply
        never emits a partial window; neither do we)."""
        return len(self._buf)


def run_microbatch(cfg: BenchmarkConfig, broker: FileBroker,
                   ad_to_campaign: dict[str, str],
                   campaigns: list[str] | None = None,
                   redis: RedisLike | None = None,
                   barrier=None,
                   max_windows: int | None = None,
                   input_format: str = "json"
                   ) -> tuple[dict[int, np.ndarray], list[PartitionResult]]:
    """Drive ``map.partitions`` mapper threads over the broker topic.

    Returns ``(merged, results)``: merged per-campaign counts keyed by
    window ordinal (partition partials summed — the unifier /
    ``reduce.partitions`` role, the host analog of the psum merge) and
    the per-partition results.
    When ``redis`` is given, each partition dumps its latency map in the
    fork's hash format at close.
    """
    P = cfg.map_partitions
    have = set(broker.partitions(cfg.kafka_topic))
    missing = [p for p in range(P) if p not in have]
    if missing:
        raise ValueError(
            f"map.partitions={P} but broker topic '{cfg.kafka_topic}' has "
            f"no partition(s) {missing} (found {sorted(have)}); generate "
            f"the dataset with a matching partition count")
    barrier = barrier or LocalWindowBarrier(P)
    # THE single reset point (see RedisWindowBarrier docstring): clear any
    # prior run's residue before the first partition can arrive.
    barrier.reset()
    # ONE ENCODER PER MAPPER THREAD: encoders carry mutable intern state
    # (user/page maps, rebase origin) that is not thread-safe — sharing
    # one across concurrently-encoding partitions silently corrupts
    # parses (observed as nondeterministic counts).  The join table is
    # deterministic from the mapping, so one device copy is shared.
    encoders = [make_encoder(ad_to_campaign, campaigns,
                             divisor_ms=cfg.jax_time_divisor_ms,
                             lateness_ms=cfg.jax_allowed_lateness_ms,
                             use_native=cfg.jax_use_native_encoder)
                for _ in range(P)]
    join_table_dev = jnp.asarray(encoders[0].join_table)
    mappers = [MicroBatchMapper(cfg, encoders[p], join_table_dev, barrier, p,
                                input_format=input_format)
               for p in range(P)]
    # Warm the kernel before spawning threads: P mappers would otherwise
    # race into the same first jit-compile concurrently (tracing is not
    # reliably thread-safe for an identical fresh signature).
    psize = mappers[0].partition_size
    window_campaign_counts(
        join_table_dev, np.zeros(psize, np.int32),
        np.full(psize, -1, np.int32), np.zeros(psize, bool),
        num_campaigns=encoders[0].num_campaigns).block_until_ready()

    limit = max_windows * psize if max_windows else None
    errors: list[BaseException] = []

    def drive(p: int) -> None:
        try:
            with broker.reader(cfg.kafka_topic, p) as reader:
                fed = 0
                while True:
                    want = (min(4096, limit - fed)
                            if limit is not None else 4096)
                    if want <= 0:
                        break
                    lines = reader.poll(max_records=want)
                    if not lines:
                        break
                    mappers[p].feed(lines)
                    fed += len(lines)
            # end-of-stream: no further window can assemble without this
            # partition; release any peers parked at the rendezvous
            barrier.abort()
        except threading.BrokenBarrierError:
            pass  # a peer hit end-of-stream; our partial window is dropped
        except BaseException as e:  # surface thread failures to the caller
            errors.append(e)
            barrier.abort()

    threads = [threading.Thread(target=drive, args=(p,), daemon=True)
               for p in range(P)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]

    merged: dict[int, np.ndarray] = {}
    for m in mappers:
        for k, counts in m.result.counts.items():
            if k in merged:
                merged[k] = merged[k] + counts
            else:
                merged[k] = counts

    if redis is not None and cfg.redis_hashtable:
        for m in mappers:
            dump_latency_hash(redis, cfg.redis_hashtable, m.result.latency,
                              running_time_ms=m.result.running_time_ms)
    return merged, [m.result for m in mappers]
