"""Count-based micro-batch mode: the fork's barrier-aligned windows.

The reference fork's research vehicle (``MockWindowedFlatMap``,
``AdvertisingTopologyNative.java:167-254``) replaces event-time windows
with *count-based* ones: each of ``map.partitions`` parallel mappers
buffers ``window.size / map.partitions`` events, then all partitions
rendezvous at a window barrier; the last partition to arrive becomes the
owner and stamps the window's start time into Redis, the rest spin on
``HGET start_time``.  Every event is tagged with that shared stamp, and the
downstream processor records per-window latency ``now − start`` which it
dumps to a Redis hash at job close (``CampaignProcessor``, ``:477-533``).

This module re-expresses that design TPU-first:

- the per-window work (filter "view" -> join -> per-campaign count) is one
  jitted segment-sum over the whole window — a micro-batch IS a window, so
  the keyed shuffle collapses to a single ``[C]`` count vector per
  partition, merged across partitions by addition (the host analog of the
  ``psum`` merge; the network shuffle never happens);
- in-process partitions align on a ``threading.Barrier`` whose action
  stamps the window (``LocalWindowBarrier``) — the device-step-alignment
  analog; distributed processes use ``RedisWindowBarrier``, the fork's
  protocol with one fix: the fork HDELs a *shared* ``start_time`` field on
  window entry, which lets a late-arriving partition delete the stamp the
  owner just wrote (a real race in the reference, SURVEY.md §5.2); here
  stamps are per-window-index fields ``start_time:<k>``, so nothing is
  ever deleted while being waited on;
- the latency dump keeps the fork's exact hash schema
  (``redis.hashtable``: ``thread_idx``, ``running_time:<i>``,
  ``<windowStart>:<i>`` -> latency) via ``dump_latency_hash``.

Unlike the fork (where every parallel source re-reads the *same* events
file, ``FileBasedDataSource`` x ``map.partitions``), partitions here each
consume their own broker partition — real data parallelism.
"""

from __future__ import annotations

import functools
import glob
import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.encode.native_encoder import make_encoder
from streambench_tpu.io.journal import FileBroker
from streambench_tpu.io.redis_schema import RedisLike, dump_latency_hash
from streambench_tpu.ops import hll
from streambench_tpu.utils.ids import now_ms


# ----------------------------------------------------------------------
# window barriers
# ----------------------------------------------------------------------

class LocalWindowBarrier:
    """In-process rendezvous: the barrier action stamps the window start.

    The action runs exactly once per generation before any waiter is
    released, so every partition reads the same stamp — the same role the
    fork's "last HINCRBY arrival" owner plays.
    """

    def __init__(self, n_partitions: int, timeout_s: float = 60.0,
                 on_window=None):
        self._stamps: dict[int, int] = {}
        self._timeout = timeout_s
        self.ended = False  # abort() was an end-of-stream, not a timeout
        self._on_window = on_window
        # Resume support: a run restored from a window-k checkpoint keeps
        # window ordinals, but this barrier's generations restart at 0 —
        # base_window rebases stamp keys so arrive(k + g) finds them.
        self.base_window = 0
        self._barrier = threading.Barrier(n_partitions, action=self._stamp)

    def _stamp(self) -> None:
        # generations are sequential: all partitions are at window k here
        k = self.base_window + len(self._stamps)
        self._stamps[k] = now_ms()
        if self._on_window is not None:
            # Every partition is parked in wait(): windows 0..k-1 are
            # fully folded and no result dict can mutate concurrently —
            # the one quiescent point in the run (used for checkpoints).
            self._on_window(k)

    def arrive(self, window_idx: int) -> int:
        try:
            self._barrier.wait(self._timeout)
        except threading.BrokenBarrierError:
            # CPython Barrier race: a peer that already passed this
            # generation can abort() (end-of-stream) before WE re-check
            # the barrier state on wake-up, poisoning a generation that
            # in fact completed.  The stamp discriminates exactly: the
            # barrier action ran (stamp exists) iff our generation
            # completed — return it; only a genuinely un-assembled
            # window falls through.
            if window_idx in self._stamps:
                return self._stamps[window_idx]
            if self.ended:
                raise  # normal end-of-stream release (drive() swallows it)
            # Barrier.wait's own timeout also breaks the barrier; surface
            # it as the error it is instead of a silent partial result.
            raise TimeoutError(
                f"window barrier {window_idx}: a partition failed to "
                f"arrive within {self._timeout}s") from None
        return self._stamps[window_idx]

    def abort(self) -> None:
        """End the run: once any partition hits end-of-stream no further
        window can ever assemble (the barrier needs all parties), so
        waiting peers are released with ``BrokenBarrierError`` and their
        in-flight window is dropped — consistent with the no-partial-
        windows rule."""
        self.ended = True
        self._barrier.abort()

    def reset(self) -> None:
        """No-op: a fresh object IS a fresh barrier (state is in-process)."""


class RedisWindowBarrier:
    """The fork's Redis barrier, with per-window stamp keys (see module
    docstring for the delete-race fix).  Protocol per window ``k``:

    - ``HINCRBY <table> partition_count 1``; the arrival that brings the
      count to ``n_partitions`` resets it to 0 and becomes the owner
      (``start_new_window``, ``AdvertisingTopologyNative.java:228-238``);
    - owner: ``HSET <table> start_time:<k> now`` (``finish_window``);
    - others: 1 ms-sleep spin on ``HGET start_time:<k>`` (``wait_window``).

    Construction is **side-effect-free**: residue from a prior run
    (``partition_count`` left by an aborted run's already-arrived spinners,
    a stale ``aborted`` broadcast) is cleared by ``reset()``, which the run
    *driver* calls exactly once before any partition starts — a
    per-partition constructor clear would itself race with peers already
    arriving, and can erase a live run's end-of-stream broadcast.  (The
    fork has both flaws and leans on the harness FLUSHALL between runs.)
    Runs sharing one hashtable can alternatively be isolated with
    ``run_id``, which namespaces every barrier field.
    """

    def __init__(self, redis: RedisLike, hashtable: str, n_partitions: int,
                 poll_interval_s: float = 0.001, timeout_s: float = 60.0,
                 run_id: str = ""):
        self.redis = redis
        self.table = hashtable
        self.n = n_partitions
        self._poll = poll_interval_s
        self._timeout = timeout_s
        suffix = f":{run_id}" if run_id else ""
        self._f_count = "partition_count" + suffix
        self._f_abort = "aborted" + suffix
        self._f_stamp = "start_time" + suffix

    def reset(self) -> None:
        """Clear this run's barrier fields.  MUST be called exactly once,
        by the driver, before any partition can arrive.

        Clears the per-window stamps too: a stale ``start_time:<k>`` from
        a prior run would satisfy a spinner *instantly* — partitions would
        stop rendezvousing at all and every event would carry the previous
        run's stamp (garbage latencies)."""
        self.redis.execute("HDEL", self.table, self._f_count)
        self.redis.execute("HDEL", self.table, self._f_abort)
        prefix = self._f_stamp + ":"
        flat = (self.redis.hgetall(self.table)
                if hasattr(self.redis, "hgetall") else {})
        for name in flat:
            if name.startswith(prefix):
                self.redis.execute("HDEL", self.table, name)

    def arrive(self, window_idx: int) -> int:
        if self.redis.execute("HGET", self.table, self._f_abort) is not None:
            raise threading.BrokenBarrierError
        my = int(self.redis.execute("HINCRBY", self.table, self._f_count, 1))
        field_ = f"{self._f_stamp}:{window_idx}"
        if my == self.n:
            self.redis.execute("HSET", self.table, self._f_count, "0")
            stamp = now_ms()
            self.redis.execute("HSET", self.table, field_, str(stamp))
            return stamp
        deadline = time.monotonic() + self._timeout
        while True:
            res, ab = self.redis.pipeline_execute(
                [("HGET", self.table, field_),
                 ("HGET", self.table, self._f_abort)])
            if res is not None:
                return int(res)
            if ab is not None:
                # a peer hit end-of-stream: this window can never assemble
                raise threading.BrokenBarrierError
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"window barrier {window_idx}: no stamp after "
                    f"{self._timeout}s (partition died?)")
            time.sleep(self._poll)

    def abort(self) -> None:
        """End-of-stream broadcast: release peers parked in ``arrive``
        (their in-flight window is dropped, matching the local barrier)."""
        self.redis.execute("HSET", self.table, self._f_abort, "1")


# ----------------------------------------------------------------------
# the per-window device kernel
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_campaigns", "view_type"))
def window_campaign_counts(join_table, ad_idx, event_type, valid,
                           *, num_campaigns: int, view_type: int = 0):
    """One micro-batch window -> per-campaign view counts ``[C]``.

    The whole fork chain (EventFilterBolt -> project -> RedisJoinBolt ->
    keyBy(campaign) -> count) as a single masked segment-sum: the keyed
    shuffle is just a scatter-add index.
    """
    campaign = join_table[ad_idx]
    mask = valid & (event_type == view_type) & (campaign >= 0)
    idx = jnp.where(mask, campaign, num_campaigns)  # OOB rows dropped
    return jnp.zeros((num_campaigns,), jnp.int32).at[idx].add(1, mode="drop")


@functools.partial(jax.jit, static_argnames=("num_campaigns",
                                             "num_registers", "view_type"))
def window_campaign_hll(join_table, ad_idx, user_idx, event_type, valid,
                        *, num_campaigns: int, num_registers: int,
                        view_type: int = 0):
    """One micro-batch window -> per-campaign HLL registers ``[C, R]``.

    The sketch variant of ``window_campaign_counts`` (BASELINE config #2
    under the fork's count-window mode): the scatter-add becomes a
    scatter-max of splitmix ranks.  Partition partials merge by
    elementwise max — the pmax-shaped unifier — and estimates are taken
    from the merged registers per window.
    """
    C, R = num_campaigns, num_registers
    p = R.bit_length() - 1
    campaign = join_table[ad_idx]
    mask = valid & (event_type == view_type) & (campaign >= 0)
    h = hll.splitmix32(user_idx)
    j = (h & jnp.uint32(R - 1)).astype(jnp.int32)
    rank = hll._rank(h, p)
    flat = jnp.where(mask, campaign * R + j, C * R)
    return (jnp.zeros((C * R,), jnp.int32)
            .at[flat].max(rank, mode="drop").reshape(C, R))


class _EngineFamily:
    """Per-window fold + cross-partition merge for one engine family."""

    def __init__(self, name: str, fold, merge, finalize):
        self.name = name
        self.fold = fold          # (encoder_batch) -> np.ndarray
        self.merge = merge        # (partial, partial) -> partial
        self.finalize = finalize  # merged partial -> [C] int counts


def _make_family(name: str, encoder, join_table_dev,
                 registers: int = 128) -> _EngineFamily:
    C = encoder.num_campaigns
    if name == "exact":
        return _EngineFamily(
            "exact",
            fold=lambda b: np.asarray(window_campaign_counts(
                join_table_dev, b.ad_idx, b.event_type, b.valid,
                num_campaigns=C)),
            merge=lambda a, b: a + b,
            finalize=lambda m: m)
    if name == "hll":
        if registers & (registers - 1):
            raise ValueError("num_registers must be a power of two")
        # Stateless id hashing: per-partition encoders would otherwise
        # intern the same user to different indices, and the register
        # merge across partitions would count one user several times.
        encoder.set_hash_ids(True)
        return _EngineFamily(
            "hll",
            fold=lambda b: np.asarray(window_campaign_hll(
                join_table_dev, b.ad_idx, b.user_idx, b.event_type,
                b.valid, num_campaigns=C, num_registers=registers)),
            merge=np.maximum,
            finalize=lambda m: np.asarray(
                jnp.round(hll.estimate(jnp.asarray(m)))).astype(np.int64))
    raise ValueError(
        f"micro-batch mode supports engine families 'exact' and 'hll'; "
        f"'{name}' has no count-window form (sliding windows need a time "
        f"axis and session windows a gap axis — the fork's mode is "
        f"count-based, AdvertisingTopologyNative.java:200-201)")


# ----------------------------------------------------------------------
# per-partition mapper + multi-partition driver
# ----------------------------------------------------------------------

@dataclass
class PartitionResult:
    partition: int
    windows: int = 0
    events: int = 0
    started_ms: int = 0    # first window start stamp
    finished_ms: int = 0   # completion time of the last window
    # window index -> per-campaign counts [C].  Indexed by window ordinal,
    # not stamp: in catchup runs consecutive windows can share a
    # millisecond and stamp-keyed state would silently merge them (the
    # fork has exactly this hazard — its latency map is stamp-keyed).
    counts: dict[int, np.ndarray] = field(default_factory=dict)
    # window index -> barrier stamp (shared across partitions)
    stamps: dict[int, int] = field(default_factory=dict)
    # window start stamp -> last observed latency (now - start), fork style
    latency: dict[int, int] = field(default_factory=dict)
    # window index -> broker byte offset after the window's last line
    # (the checkpoint unit: resume re-opens the reader here)
    offsets: dict[int, int] = field(default_factory=dict)

    @property
    def running_time_ms(self) -> int:
        return max(self.finished_ms - self.started_ms, 0)


class MicroBatchMapper:
    """One map partition: buffer ``partition_size`` lines, rendezvous,
    fold the window on device, record latency."""

    def __init__(self, cfg: BenchmarkConfig, encoder, join_table_dev,
                 barrier, partition: int, input_format: str = "json",
                 family: _EngineFamily | None = None):
        if cfg.window_size % cfg.map_partitions:
            raise ValueError(
                f"window.size {cfg.window_size} not divisible by "
                f"map.partitions {cfg.map_partitions}")
        self.partition_size = cfg.window_size // cfg.map_partitions
        self.encoder = encoder
        self.join_table_dev = join_table_dev
        self.barrier = barrier
        self.family = family or _make_family("exact", encoder,
                                             join_table_dev)
        # "json" for generator journals; "tbl" for the fork's pipe-separated
        # events files (AdvertisingTopologyNative.java:210: "u|p|ad|...")
        self._encode = (encoder.encode if input_format == "json"
                        else encoder.encode_tbl)
        self.result = PartitionResult(partition)
        self._buf: list[bytes] = []
        self._window_idx = 0
        self._bytes = 0  # broker bytes consumed (lines + newlines)

    def feed(self, lines: list[bytes]) -> None:
        for line in lines:
            self._buf.append(line)
            self._bytes += len(line) + 1
            if len(self._buf) == self.partition_size:
                self._close_window()

    def _close_window(self) -> None:
        start = self.barrier.arrive(self._window_idx)
        batch = self._encode(self._buf, self.partition_size)
        counts = self.family.fold(batch)
        r = self.result
        r.counts[self._window_idx] = counts
        r.stamps[self._window_idx] = start
        r.offsets[self._window_idx] = self._bytes
        done = now_ms()
        r.latency[start] = done - start
        if not r.started_ms:
            r.started_ms = start
        r.finished_ms = done
        r.windows += 1
        r.events += len(self._buf)
        self._buf.clear()
        self._window_idx += 1

    @property
    def leftover(self) -> int:
        """Events short of a full window at end of stream (the fork simply
        never emits a partial window; neither do we)."""
        return len(self._buf)


class MicroBatchCheckpointer:
    """Window-boundary snapshots of a micro-batch run, as INCREMENTAL
    chunks.

    Chunk ``mb-<k>.npz`` holds, per partition, only the windows since
    the previous chunk (``[k_from, k)``): their stacked partials and
    stamps, plus the cumulative small state (latency map, counters, and
    the broker byte offset after window ``k-1``'s last line).  Chunking
    keeps each save O(windows since last save) — a full-history rewrite
    would grow O(k) per save inside the window barrier's action, whose
    waiters carry a 60 s timeout, and would bill ever-growing fsync
    pauses to measured windows.  Snapshots are written inside the
    barrier action (the one quiescent point: all partitions parked,
    windows ``0..k-1`` final), so they need no locking; single-process
    (``LocalWindowBarrier``) runs only.  ``load`` replays the chunk
    chain (contiguity checked) and seeds the run to continue at the
    last chunk's ``k``.
    """

    def __init__(self, directory: str):
        self.dir = directory
        self._saved_upto = 0
        os.makedirs(directory, exist_ok=True)

    def _files(self) -> list[str]:
        return sorted(glob.glob(os.path.join(self.dir, "mb-*.npz")))

    def save(self, k: int, mappers, meta: dict) -> None:
        k0 = self._saved_upto
        if k <= k0:
            return  # resumed run re-arrives at an already-saved window
        arrays: dict[str, np.ndarray] = {}
        per_part = []
        for m in mappers:
            r = m.result
            arrays[f"counts_{r.partition}"] = np.stack(
                [r.counts[w] for w in range(k0, k)])
            stamps = [r.stamps[w] for w in range(k0, k)]
            chunk_stamps = set(stamps)
            per_part.append({
                "partition": r.partition,
                "stamps": stamps,
                # ONLY this chunk's windows, looked up by the chunk's
                # own stamps so save cost is O(chunk) — iterating the
                # cumulative map would still grow O(total windows) per
                # save inside the barrier action; load() merges chunks
                "latency": sorted((s, r.latency[s]) for s in chunk_stamps
                                  if s in r.latency),
                "offset": r.offsets[k - 1],
                "events": r.events, "windows": r.windows,
                "started_ms": r.started_ms, "finished_ms": r.finished_ms,
            })
        path = os.path.join(self.dir, f"mb-{k:08d}.npz")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, meta=np.frombuffer(json.dumps(
                {"k_from": k0, "k": k, "parts": per_part, **meta}
            ).encode(), np.uint8), **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._saved_upto = k

    def load(self) -> tuple[int, dict, dict[int, np.ndarray]] | None:
        files = self._files()
        if not files:
            return None
        chunks: dict[int, list[np.ndarray]] = {}
        stamps: dict[int, list[int]] = {}
        latency: dict[int, dict] = {}
        expect = 0
        meta = None
        for path in files:
            with np.load(path) as z:
                meta = json.loads(z["meta"].tobytes().decode())
                if meta["k_from"] != expect:
                    raise ValueError(
                        f"checkpoint chain broken at {path}: chunk starts "
                        f"at window {meta['k_from']}, expected {expect} "
                        f"(missing/deleted chunk file?)")
                expect = meta["k"]
                for p in meta["parts"]:
                    chunks.setdefault(p["partition"], []).append(
                        z[f"counts_{p['partition']}"])
                    stamps.setdefault(p["partition"], []).extend(
                        p["stamps"])
                    # per-chunk latency entries merge across the chain
                    # (later chunks win for a re-observed stamp)
                    latency.setdefault(p["partition"], {}).update(
                        dict(p["latency"]))
        for p in meta["parts"]:
            p["stamps"] = stamps[p["partition"]]
            p["latency"] = sorted(latency[p["partition"]].items())
        counts = {part: np.concatenate(cs) for part, cs in chunks.items()}
        self._saved_upto = meta["k"]
        return meta["k"], meta, counts

    def seed(self, mappers, meta: dict,
             counts: dict[int, np.ndarray]) -> None:
        """Restore mapper state from a loaded snapshot (before threads
        start).  Readers must then be opened at each result's
        ``offsets[k-1]``."""
        k = meta["k"]
        for m, pm in zip(mappers, meta["parts"]):
            r = m.result
            assert r.partition == pm["partition"]
            for w in range(k):
                r.counts[w] = counts[r.partition][w]
                r.stamps[w] = pm["stamps"][w]
            r.latency.update({int(s): int(l) for s, l in pm["latency"]})
            r.offsets[k - 1] = pm["offset"]
            r.events = pm["events"]
            r.windows = pm["windows"]
            r.started_ms = pm["started_ms"]
            r.finished_ms = pm["finished_ms"]
            m._window_idx = k
            m._bytes = pm["offset"]


def run_microbatch(cfg: BenchmarkConfig, broker: FileBroker,
                   ad_to_campaign: dict[str, str],
                   campaigns: list[str] | None = None,
                   redis: RedisLike | None = None,
                   barrier=None,
                   max_windows: int | None = None,
                   input_format: str = "json",
                   engine: str = "exact",
                   registers: int = 128,
                   checkpoint_dir: str | None = None,
                   checkpoint_every: int = 16,
                   ) -> tuple[dict[int, np.ndarray], list[PartitionResult]]:
    """Drive ``map.partitions`` mapper threads over the broker topic.

    Returns ``(merged, results)``: merged per-campaign counts keyed by
    window ordinal (partition partials summed for exact counts, register
    pmax + estimate for ``engine="hll"`` — the unifier /
    ``reduce.partitions`` role, the host analog of the psum/pmax merge)
    and the per-partition results.
    When ``redis`` is given, each partition dumps its latency map in the
    fork's hash format at close.
    ``checkpoint_dir`` enables window-boundary snapshots every
    ``checkpoint_every`` windows and resume-from-newest on entry
    (single-process runs only).
    """
    P = cfg.map_partitions
    have = set(broker.partitions(cfg.kafka_topic))
    missing = [p for p in range(P) if p not in have]
    if missing:
        raise ValueError(
            f"map.partitions={P} but broker topic '{cfg.kafka_topic}' has "
            f"no partition(s) {missing} (found {sorted(have)}); generate "
            f"the dataset with a matching partition count")
    ckpt = MicroBatchCheckpointer(checkpoint_dir) if checkpoint_dir else None
    if ckpt is not None and barrier is not None:
        raise ValueError(
            "micro-batch checkpointing requires the in-process barrier "
            "(snapshots are taken in its action, where all partitions are "
            "quiescent); it does not compose with a custom/Redis barrier")
    if ckpt is not None:
        # The id digest binds the snapshot to the campaign/ad universe its
        # count columns are keyed to: resuming against regenerated ids
        # (e.g. lost workdir files + a fresh -n seed) would otherwise
        # silently merge restored rows with columns for DIFFERENT
        # campaigns.
        h = hashlib.sha1()
        for ad, c in sorted(ad_to_campaign.items()):
            h.update(f"{ad}>{c};".encode())
        for c in campaigns or ():
            h.update(f"#{c}".encode())
        mb_meta = {"engine": engine, "window_size": cfg.window_size,
                   "map_partitions": P,
                   "registers": registers if engine == "hll" else 0,
                   "ids_digest": h.hexdigest()[:16]}
        loaded = ckpt.load()
        if loaded is not None and loaded[1] is not None:
            got = {key: loaded[1].get(key) for key in mb_meta}
            if got != mb_meta:
                raise ValueError(
                    f"checkpoint geometry {got} != run config {mb_meta}; "
                    f"restart with the original config or a fresh "
                    f"checkpoint dir")

        def on_window(k: int) -> None:
            if k and k % checkpoint_every == 0:
                ckpt.save(k, mappers, mb_meta)

        barrier = LocalWindowBarrier(P, on_window=on_window)
    else:
        loaded = None
        barrier = barrier or LocalWindowBarrier(P)
    # THE single reset point (see RedisWindowBarrier docstring): clear any
    # prior run's residue before the first partition can arrive.
    barrier.reset()
    # ONE ENCODER PER MAPPER THREAD: encoders carry mutable intern state
    # (user/page maps, rebase origin) that is not thread-safe — sharing
    # one across concurrently-encoding partitions silently corrupts
    # parses (observed as nondeterministic counts).  The join table is
    # deterministic from the mapping, so one device copy is shared.
    encoders = [make_encoder(ad_to_campaign, campaigns,
                             divisor_ms=cfg.jax_time_divisor_ms,
                             lateness_ms=cfg.jax_allowed_lateness_ms,
                             use_native=cfg.jax_use_native_encoder)
                for _ in range(P)]
    join_table_dev = jnp.asarray(encoders[0].join_table)
    families = [_make_family(engine, encoders[p], join_table_dev,
                             registers=registers) for p in range(P)]
    mappers = [MicroBatchMapper(cfg, encoders[p], join_table_dev, barrier, p,
                                input_format=input_format,
                                family=families[p])
               for p in range(P)]
    resume_offsets = [0] * P
    if ckpt is not None and loaded is not None:
        k0, meta0, counts0 = loaded
        ckpt.seed(mappers, meta0, counts0)
        resume_offsets = [m.result.offsets[k0 - 1] if k0 else 0
                          for m in mappers]
        # the barrier's stamp generations restart at 0; rebase them so
        # arrive(window_idx=k0...) finds its stamps
        barrier.base_window = k0
    # Warm the kernel before spawning threads: P mappers would otherwise
    # race into the same first jit-compile concurrently (tracing is not
    # reliably thread-safe for an identical fresh signature).
    psize = mappers[0].partition_size
    C = encoders[0].num_campaigns
    if engine == "hll":
        window_campaign_hll(
            join_table_dev, np.zeros(psize, np.int32),
            np.zeros(psize, np.int32), np.full(psize, -1, np.int32),
            np.zeros(psize, bool), num_campaigns=C,
            num_registers=registers).block_until_ready()
    else:
        window_campaign_counts(
            join_table_dev, np.zeros(psize, np.int32),
            np.full(psize, -1, np.int32), np.zeros(psize, bool),
            num_campaigns=C).block_until_ready()

    limit = max_windows * psize if max_windows else None
    errors: list[BaseException] = []

    def drive(p: int) -> None:
        try:
            with broker.reader(cfg.kafka_topic, p) as reader:
                if resume_offsets[p]:
                    reader.seek(resume_offsets[p])
                fed = 0
                while True:
                    want = (min(4096, limit - fed)
                            if limit is not None else 4096)
                    if want <= 0:
                        break
                    lines = reader.poll(max_records=want)
                    if not lines:
                        break
                    mappers[p].feed(lines)
                    fed += len(lines)
            # end-of-stream: no further window can assemble without this
            # partition; release any peers parked at the rendezvous
            barrier.abort()
        except threading.BrokenBarrierError:
            pass  # a peer hit end-of-stream; our partial window is dropped
        except BaseException as e:  # surface thread failures to the caller
            errors.append(e)
            barrier.abort()

    threads = [threading.Thread(target=drive, args=(p,), daemon=True)
               for p in range(P)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]

    fam = families[0]
    merged: dict[int, np.ndarray] = {}
    for m in mappers:
        for k, partial in m.result.counts.items():
            if k in merged:
                merged[k] = fam.merge(merged[k], partial)
            else:
                merged[k] = partial
    merged = {k: fam.finalize(v) for k, v in merged.items()}

    if redis is not None and cfg.redis_hashtable:
        for m in mappers:
            dump_latency_hash(redis, cfg.redis_hashtable, m.result.latency,
                              running_time_ms=m.result.running_time_ms)
    return merged, [m.result for m in mappers]
