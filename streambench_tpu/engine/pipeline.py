"""The TPU engine: encode -> jitted window step -> delta flush -> Redis.

This class is the peer of one engine topology in the reference (e.g.
``AdvertisingTopology`` for Storm) — but where a JVM engine is a DAG of
concurrently-scheduled operators, here the whole per-batch pipeline is a
single compiled XLA program (`ops.windowcount.step`) and the only host code
is string encoding and the Redis flusher.

Correctness invariant (ring reuse): between two flushes the engine must not
let the stream's *event-time* span exceed the ring's safe span, or a new
window could claim a slot whose counts were never drained.  The engine
tracks the max encoded timestamp on the host (no device sync needed) and
auto-flushes device deltas into a host-side pending buffer when the span
guard trips.  Wall-clock flush cadence to Redis stays the reference's 1 Hz
(``CampaignProcessorCommon.java:41-54``) regardless.
"""

from __future__ import annotations

from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.encode.native_encoder import make_encoder
from streambench_tpu.io.redis_schema import (
    RedisLike,
    dump_latency_hash,
    write_windows_pipelined,
)
from streambench_tpu.metrics import LatencyTracker
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.trace import Tracer
from streambench_tpu.utils.ids import now_ms


# One-hot materializes a [B, C*W] comparison per step — MXU-friendly while
# C*W is a few thousand cells (C=100 campaigns x W=16 slots = 1,600) but
# catastrophic at BASELINE config #5's C=1e6 (a [1024, 1.6e7] intermediate
# per step).  Above this cell bound scatter-add always wins.
ONEHOT_MAX_CELLS = 32_768


def default_method(num_cells: int | None = None) -> str:
    """Scatter-add on CPU or for large state; one-hot reduction on TPU
    (MXU-friendly) while ``num_cells = C*W`` stays under the bound."""
    if jax.default_backend() not in ("tpu", "axon"):
        return "scatter"
    if num_cells is not None and num_cells > ONEHOT_MAX_CELLS:
        return "scatter"
    return "onehot"


class AdAnalyticsEngine:
    """Exact per-(campaign, 10 s window) view counting — BASELINE config #1."""

    # Subclasses whose pending values are absolute snapshots (not deltas)
    # set this so the Redis writer HSETs instead of HINCRBYs.
    absolute_counts = False
    # Checkpoint compatibility class: restore refuses a snapshot from a
    # different family (engines with different device state would silently
    # misinterpret each other's arrays).  The sharded engine shares
    # "exact" with the base deliberately — its state is the same counts.
    ENGINE_FAMILY = "exact"

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 method: str | None = None,
                 input_format: str = "json"):
        self.cfg = cfg
        self.redis = redis
        self.divisor = cfg.jax_time_divisor_ms
        self.lateness = cfg.jax_allowed_lateness_ms
        self.encoder = make_encoder(ad_to_campaign, campaigns,
                                    divisor_ms=self.divisor,
                                    lateness_ms=self.lateness,
                                    use_native=cfg.jax_use_native_encoder)
        self.join_table = jnp.asarray(self.encoder.join_table)
        self.W = cfg.jax_window_slots
        self.method = method or default_method(
            self.encoder.num_campaigns * self.W)
        self.batch_size = cfg.jax_batch_size
        self._encode = (self.encoder.encode if input_format == "json"
                        else self.encoder.encode_tbl)
        if self.W * self.divisor <= self.lateness + 2 * self.divisor:
            raise ValueError(
                f"window ring too small: {self.W} slots x {self.divisor} ms "
                f"must exceed lateness {self.lateness} ms + 2 windows")
        # Safe event-time span between device drains.
        self._span_guard = self.W * self.divisor - self.lateness - 2 * self.divisor
        self.state = wc.init_state(self.encoder.num_campaigns, self.W)

        # host-side bookkeeping
        self._span_start: int | None = None   # min unflushed event time (abs)
        # pending Redis deltas: (campaign_idx, abs_window_ts) -> count
        self._pending: dict[tuple[int, int], int] = defaultdict(int)
        self.events_processed = 0
        self.windows_written = 0
        self.started_ms = now_ms()
        self.last_event_ms = self.started_ms
        # fork-style latency accounting: abs_window_ts -> last time_updated
        self.window_latency: dict[int, int] = {}
        # stage spans (SURVEY.md §5.1) + Apex-style decile accounting (§5.5)
        self.tracer = Tracer()
        self.latency_tracker = LatencyTracker(window_ms=self.divisor)

    # ------------------------------------------------------------------
    def process_lines(self, lines: list[bytes]) -> int:
        """Encode + fold up to one batch worth of lines.  Returns rows used."""
        for off in range(0, max(len(lines), 1), self.batch_size):
            chunk = lines[off:off + self.batch_size]
            if not chunk:
                break
            with self.tracer.span("encode"):
                batch = self._encode(chunk, self.batch_size)
            if batch.n == 0:
                continue
            self._fold(batch)
        return len(lines)

    def _fold(self, batch) -> None:
        """Ring-guarded fold of one encoded batch, splitting when needed.

        Two span hazards can corrupt the ring: (a) the batch stretches the
        *unflushed* span past the safe limit -> drain first; (b) the batch
        ALONE spans more event time than the ring can hold (sparse or
        low-rate streams: batch_size x inter-event gap > ring span) -> no
        drain can help; halve and recurse.  Halving keeps the jit shape
        set bounded (log2(B) distinct shapes, each compiled once).
        """
        vt = batch.event_time[:batch.n]
        batch_max = int(vt.max()) + batch.base_time_ms
        batch_min = int(vt.min()) + batch.base_time_ms
        if batch_max - batch_min > self._span_guard and batch.n > 1:
            for half in self._halves(batch):
                if half.n:
                    self._fold(half)
            return
        if self._span_start is None:
            self._span_start = batch_min
        # Ring-reuse guard: drain device deltas BEFORE this batch if its
        # max would stretch the unflushed span past the safe limit.
        if batch_max - self._span_start > self._span_guard:
            with self.tracer.span("drain"):
                self._drain_device()
            if self._span_start is None or batch_min < self._span_start:
                self._span_start = batch_min
        with self.tracer.span("device_step"):
            # async dispatch: the span covers transfer + enqueue, not
            # device completion (that overlaps the next encode — the
            # pipeline-parallel analog, SURVEY.md §2)
            self._device_step(batch)
        self.events_processed += batch.n
        self.last_event_ms = now_ms()

    @staticmethod
    def _halves(batch):
        """Split an encoded batch into two fixed-shape halves (valid rows
        are compacted to the front, so column slices stay consistent)."""
        import dataclasses

        B = batch.batch_size
        B0 = B // 2
        n0 = min(batch.n, B0)
        cols = ("ad_idx", "event_type", "event_time", "user_idx",
                "page_idx", "ad_type", "valid")
        lo = dataclasses.replace(
            batch, **{c: getattr(batch, c)[:B0] for c in cols}, n=n0)
        hi = dataclasses.replace(
            batch, **{c: getattr(batch, c)[B0:] for c in cols},
            n=batch.n - n0)
        return lo, hi

    # ------------------------------------------------------------------
    def _device_step(self, batch) -> None:
        """Fold one ``EncodedBatch`` into device state (subclass hook:
        the sharded engine swaps in the mesh version; sketch engines use
        additional columns like ``user_idx``)."""
        self.state = wc.step(
            self.state, self.join_table,
            jnp.asarray(batch.ad_idx), jnp.asarray(batch.event_type),
            jnp.asarray(batch.event_time), jnp.asarray(batch.valid),
            divisor_ms=self.divisor, lateness_ms=self.lateness,
            method=self.method)

    # ------------------------------------------------------------------
    def _drain_device(self) -> None:
        """Pull count deltas off the device into the host pending buffer."""
        deltas, wids, self.state = wc.flush_deltas(
            self.state, divisor_ms=self.divisor, lateness_ms=self.lateness)
        deltas = np.asarray(deltas)
        wids = np.asarray(wids)
        base = self.encoder.base_time_ms or 0
        ci, si = np.nonzero(deltas)
        for c, s in zip(ci.tolist(), si.tolist()):
            wid = int(wids[s])
            if wid < 0:
                continue
            abs_ts = base + wid * self.divisor
            self._pending[(c, abs_ts)] += int(deltas[c, s])
        self._span_start = None

    def flush(self, time_updated: int | None = None) -> int:
        """Drain device + write all pending deltas to Redis.

        Stamps ``time_updated`` at actual write time (``core.clj:149``
        defines latency truth as ``time_updated − window_ts``).  Returns
        window rows written.
        """
        with self.tracer.span("drain"):
            self._drain_device()
        if not self._pending:
            return 0
        stamp = now_ms() if time_updated is None else time_updated
        rows = [(self.encoder.campaigns[c], ts, n)
                for (c, ts), n in self._pending.items()]
        for camp, ts, _ in rows:
            self.window_latency[ts] = stamp - ts
            self.latency_tracker.record(camp, ts, stamp)
        if self.redis is not None:
            with self.tracer.span("redis_flush"):
                write_windows_pipelined(self.redis, rows, time_updated=stamp,
                                        absolute=self.absolute_counts)
        self._pending.clear()
        self.windows_written += len(rows)
        return len(rows)

    # ------------------------------------------------------------------
    # checkpoint/resume (SURVEY.md §5.4 — absent in the reference; the
    # scan carry is fixed-shape arrays, so a snapshot is one savez)
    def _snapshot_meta(self) -> dict:
        """Host-side meta shared by every engine family's snapshot."""
        return dict(
            engine_family=self.ENGINE_FAMILY,
            base_time_ms=self.encoder.base_time_ms,
            divisor_ms=self.divisor,
            lateness_ms=self.lateness,
            window_slots=self.W,
            span_start=self._span_start,
            events_processed=self.events_processed,
            windows_written=self.windows_written,
            started_ms=self.started_ms,
            last_event_ms=self.last_event_ms,
            num_campaigns=self.encoder.num_campaigns,
        )

    def snapshot(self, offset: int) -> "Snapshot":
        """Capture exact engine state as of journal byte ``offset``."""
        from streambench_tpu.checkpoint import Snapshot

        return Snapshot(
            offset=offset,
            meta=self._snapshot_meta(),
            counts=np.asarray(self.state.counts),
            window_ids=np.asarray(self.state.window_ids),
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped),
            pending=[(c, ts, n) for (c, ts), n in self._pending.items()],
            latency=sorted(self.window_latency.items()),
        )

    def _check_geometry(self, snap: "Snapshot",
                        extra: dict[str, int] | None = None) -> None:
        """Family + ring-geometry validation.  Window ids are relative to
        divisor and base, slots to W — reinterpreting any of them silently
        corrupts counts (the span guard would be sized for the wrong
        ring), so a mismatch is a hard error, never a best-effort load."""
        fam = snap.meta.get("engine_family", "exact")
        if fam != self.ENGINE_FAMILY:
            raise ValueError(
                f"checkpoint was written by engine family {fam!r}; this "
                f"engine is {self.ENGINE_FAMILY!r} — device state is not "
                "interchangeable across families")
        checks = dict(num_campaigns=self.encoder.num_campaigns,
                      divisor_ms=self.divisor,
                      lateness_ms=self.lateness,
                      window_slots=self.W)
        checks.update(extra or {})
        for key, mine in checks.items():
            if int(snap.meta[key]) != mine:
                raise ValueError(
                    f"checkpoint {key}={snap.meta[key]} != engine {mine}; "
                    "restart with the original config or discard the "
                    "checkpoint")

    def _restore_host(self, snap: "Snapshot") -> None:
        """Re-establish every host-side field from snapshot meta."""
        self.encoder.set_base_time(snap.meta["base_time_ms"])
        self._span_start = snap.meta["span_start"]
        self.events_processed = int(snap.meta["events_processed"])
        self.windows_written = int(snap.meta["windows_written"])
        self.started_ms = int(snap.meta["started_ms"])
        self.last_event_ms = int(snap.meta["last_event_ms"])
        self._pending = defaultdict(int)
        for c, ts, n in snap.pending:
            self._pending[(int(c), int(ts))] = int(n)
        self.window_latency = {int(ts): int(v) for ts, v in snap.latency}

    def restore(self, snap: "Snapshot") -> None:
        """Reset this engine to a snapshot; caller re-tails the journal at
        ``snap.offset``."""
        self._check_geometry(snap)
        self.state = self._put_state(
            snap.counts, snap.window_ids, snap.watermark, snap.dropped)
        self._restore_host(snap)

    def _put_state(self, counts, window_ids, watermark, dropped):
        """Place restored host arrays on device (subclass hook: the sharded
        engine re-applies its mesh shardings)."""
        return wc.WindowState(
            counts=jnp.asarray(counts), window_ids=jnp.asarray(window_ids),
            watermark=jnp.int32(watermark), dropped=jnp.int32(dropped))

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Final flush + fork-style latency dump
        (``AdvertisingTopologyNative.java:521-532``)."""
        self.flush()
        if self.redis is not None and self.cfg.redis_hashtable:
            dump_latency_hash(
                self.redis, self.cfg.redis_hashtable, self.window_latency,
                running_time_ms=self.last_event_ms - self.started_ms)

    @property
    def dropped(self) -> int:
        return int(self.state.dropped)
