"""The TPU engine: encode -> jitted window step -> delta flush -> Redis.

This class is the peer of one engine topology in the reference (e.g.
``AdvertisingTopology`` for Storm) — but where a JVM engine is a DAG of
concurrently-scheduled operators, here the whole per-batch pipeline is a
single compiled XLA program (`ops.windowcount.step`) and the only host code
is string encoding and the Redis flusher.

Correctness invariant (ring reuse): between two flushes the engine must not
let the stream's *event-time* span exceed the ring's safe span, or a new
window could claim a slot whose counts were never drained.  The engine
tracks the max encoded timestamp on the host (no device sync needed) and
auto-flushes device deltas into a host-side pending buffer when the span
guard trips.  Wall-clock flush cadence to Redis stays the reference's 1 Hz
(``CampaignProcessorCommon.java:41-54``) regardless.
"""

from __future__ import annotations

import os
import queue
import threading
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.config import BenchmarkConfig
from streambench_tpu.encode.native_encoder import make_encoder
from streambench_tpu.io.redis_schema import (
    RedisLike,
    claim_epoch,
    dump_latency_hash,
    fence_key,
    read_fence,
    write_windows_pipelined,
)
from streambench_tpu.metrics import FaultCounters, LatencyTracker
from streambench_tpu.ops import windowcount as wc
from streambench_tpu.trace import Tracer
from streambench_tpu.utils.ids import now_ms


# The factored matmul method materializes [B, C] + [B, W] one-hots (not
# the [B, C*W] the "onehot" method needs), so its bound is on the campaign
# axis alone: past this, the [B, C] operand stops being worth the MXU and
# scatter-add wins (config #5's C=1e6 would be a [8192, 1e6] f32 operand —
# 32 GB).
MATMUL_MAX_CAMPAIGNS = 4_096


def default_method(num_campaigns: int | None = None) -> str:
    """Counting-kernel choice, MEASURED where a measurement exists.

    ``ops.methodbench`` caches per-backend/per-campaign-bucket winners
    (``bench.py``'s device section records them; the CI smoke runs the
    tiny-size path); an exact bucket hit decides.  Without one, the
    original heuristic: scatter-add on CPU or for large key spaces; the
    factored MXU matmul on TPU while the campaign axis stays under
    ``MATMUL_MAX_CAMPAIGNS`` (the [B, W] slot one-hot is never the
    binding operand: W is a ring of open windows, bounded by config to a
    few hundred slots)."""
    try:
        from streambench_tpu.ops import methodbench

        measured = methodbench.cached_winner(jax.default_backend(),
                                             num_campaigns)
    except Exception:
        measured = None
    if measured is not None:
        return measured
    if jax.default_backend() not in ("tpu", "axon"):
        return "scatter"
    if num_campaigns is not None and num_campaigns > MATMUL_MAX_CAMPAIGNS:
        return "scatter"
    return "matmul"


def _unique_ts(ts: np.ndarray) -> np.ndarray:
    """``np.unique`` for window-timestamp columns, without the sort
    where the value range is dense: sliding-family flushes carry
    millions of rows over only thousands of distinct divisor-aligned
    windows, and per-flush sort-based dedup was measured at ~0.5 s of a
    6 s catchup (ISSUE 12).  A bounded flag array dedups in O(n); wide
    or tiny inputs keep the sort path."""
    if ts.size < (1 << 12):
        return np.unique(ts)
    tmin = int(ts.min())
    span = int(ts.max()) - tmin + 1
    if span > 16 * ts.size or span > (1 << 26):
        return np.unique(ts)
    flags = np.zeros(span, bool)
    flags[ts - tmin] = True
    return np.flatnonzero(flags) + tmin


class _ArrayRows:
    """A flush batch as numpy columns — (campaign_idx, abs_window_ts,
    count) — plus the campaign-name table needed to write or recover
    them.  ``table`` is ``(names_blob, names_off, native_store)``."""

    __slots__ = ("ci", "ts", "cnt", "table", "campaigns")

    def __init__(self, ci, ts, cnt, table, campaigns):
        self.ci, self.ts, self.cnt = ci, ts, cnt
        self.table = table
        self.campaigns = campaigns

    def __len__(self) -> int:
        return int(self.ci.shape[0])

    def to_rows(self) -> list:
        """Expand to (campaign, ts, count) rows (failure/reclaim path
        only — the success path never leaves numpy)."""
        names = self.campaigns
        return [(names[c], int(t), int(n))
                for c, t, n in zip(self.ci.tolist(), self.ts.tolist(),
                                   self.cnt.tolist())]


class _RedisWriter:
    """Background window-writeback thread.

    The reference runs its Redis flusher on its own thread
    (``CampaignProcessorCommon.java:35-55``); here that overlaps the
    writeback with encode + device compute (the pipeline-parallel stage
    chain, SURVEY.md §2).  ``time_updated`` is stamped by THIS thread at
    actual write time (``core.clj:149`` defines latency truth), unless the
    caller pinned a stamp.  A bounded queue provides backpressure; errors
    surface on the next ``drain``/``close``.

    Sink-outage tolerance (ROBUSTNESS.md): a failed write is retained for
    reclaim (never dropped), the NEXT attempt is delayed by capped
    exponential backoff (a down sink must not be hammered at queue
    drain speed), a ``reconnect()``-capable client is re-dialed before
    retrying, and the retained buffer is coalesced by (campaign, window)
    past a high-water row count so an hours-long outage holds memory at
    O(dirty windows), not O(outage duration).

    Exactly-once mode (``exactly_once=True``, ROBUSTNESS.md
    "Exactly-once"): every flush rides ONE pipeline bracketed by fence
    records — ``intent``/``epoch`` first, the commit ``seq`` last — and
    each apply is preceded by an epoch pre-check so a superseded writer
    (an abandoned attempt's thread still draining its queue) aborts
    instead of applying stale deltas (``fence_conflicts``).  A failed
    apply whose commit fence IS on the sink actually landed end-to-end
    (the error was response-side): the retry is suppressed
    (``dedup_suppressed_flushes``) instead of double-applying.
    """

    def __init__(self, redis: RedisLike, absolute: bool, tracer: Tracer,
                 on_written, faults: "FaultCounters | None" = None,
                 retry_base_ms: int = 100, retry_cap_ms: int = 5000,
                 dirty_cap_rows: int = 1 << 18,
                 exactly_once: bool = False, fence_key: str = "",
                 epoch: int | None = None, start_seq: int = 0) -> None:
        self._redis = redis
        self._absolute = absolute
        self._tracer = tracer
        self._on_written = on_written   # (rows, stamp) latency bookkeeping
        self._faults = faults if faults is not None else FaultCounters()
        self._retry_base_ms = max(int(retry_base_ms), 1)
        self._retry_cap_ms = max(int(retry_cap_ms), self._retry_base_ms)
        self._dirty_cap_rows = max(int(dirty_cap_rows), 1)
        # exactly-once fence state (all dormant when the flag is off):
        # epoch None = claim lazily from the sink at the first apply;
        # seq continues from the sink's high-water (never reused, so the
        # landed-or-not dedup check is unambiguous)
        self._xo = bool(exactly_once)
        self._fence_key = fence_key
        self._epoch = epoch
        self._seq = int(start_seq)
        self._seq_acked = int(start_seq)
        self._fenced = False            # a newer epoch owns the sink
        self._last_attempt_seq: int | None = None
        self._consec_failures = 0
        # window/list-UUID memo across flushes (sole-writer assumption,
        # see write_windows_pipelined); only this thread touches it
        self._uuid_cache: dict = {}
        self._q: queue.Queue = queue.Queue(maxsize=8)
        self._error: BaseException | None = None
        self._lock = threading.Lock()
        # Batches whose write raised: retained for the engine to re-merge
        # into _pending (take_failed) — a transient Redis outage must not
        # permanently undercount windows.
        self._failed: list[list] = []
        self._failed_rows = 0
        # interruptible backoff sleep: close() sets this so shutdown never
        # waits out a capped backoff
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="redis-writer")
        self._thread.start()

    def _backoff_ms(self) -> int:
        """Capped exponential backoff for the current failure streak."""
        n = min(self._consec_failures, 16)  # 2**16 already >> any cap
        return min(self._retry_base_ms * (1 << max(n - 1, 0)),
                   self._retry_cap_ms)

    def _on_failure(self, rows: list, err: BaseException) -> None:
        import sys

        self._consec_failures += 1
        self._faults.inc("sink_errors")
        back = self._backoff_ms()
        self._faults.inc("sink_backoff_ms", back)
        print(f"redis writer: write of {len(rows)} rows failed "
              f"({err!r}); retained for retry, backoff {back} ms",
              file=sys.stderr, flush=True)
        with self._lock:
            self._failed.append(rows)
            self._failed_rows += len(rows)
            self._error = err
            if self._failed_rows > self._dirty_cap_rows:
                self._coalesce_failed_locked()
        # Re-dial before the next attempt: a half-open socket hangs every
        # command until its timeout; a fresh connect fails fast or works.
        reconnect = getattr(self._redis, "reconnect", None)
        if reconnect is not None:
            try:
                reconnect()
                self._faults.inc("sink_reconnects")
            except Exception:
                pass  # still down; the backoff covers it
        self._wake.wait(back / 1000.0)
        self._wake.clear()

    def _coalesce_failed_locked(self) -> None:
        """Merge the retained batches by (campaign, window) — deltas sum;
        absolute values keep the freshest (batch order is write order).
        Called with the lock held, past the high-water mark only."""
        import sys

        merged: dict[tuple, int] = {}
        for batch in self._failed:
            for camp, ts, n in batch:
                if self._absolute:
                    merged[(camp, ts)] = n
                else:
                    merged[(camp, ts)] = merged.get((camp, ts), 0) + n
        rows = [(c, ts, n) for (c, ts), n in merged.items()]
        before = self._failed_rows
        self._failed = [rows]
        self._failed_rows = len(rows)
        self._faults.inc("sink_dirty_high_water")
        print(f"redis writer: retained rows passed high water "
              f"({before} > {self._dirty_cap_rows}); coalesced to "
              f"{len(rows)} dirty windows", file=sys.stderr, flush=True)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                payload, stamp, absolute = item
                stamp = now_ms() if stamp is None else stamp
                if absolute is None:
                    absolute = self._absolute
                arrays = not isinstance(payload, list)
                fenced_out = False
                try:
                    with self._tracer.span("redis_flush"):
                        if self._xo:
                            fenced_out = not self._apply_fenced(
                                payload, stamp, absolute)
                        elif arrays:
                            # (ci, ts, cnt) numpy triple against the
                            # native store: campaign table passed once,
                            # zero per-row Python work
                            blob, off, store = payload.table
                            store.write_windows_arrays(
                                blob, off, payload.ci, payload.ts,
                                payload.cnt, str(stamp), self._absolute)
                        else:
                            write_windows_pipelined(
                                self._redis, payload, time_updated=stamp,
                                absolute=absolute,
                                cache=self._uuid_cache)
                except BaseException as e:  # retained for reclaim/retry
                    if self._xo and self._landed(self._last_attempt_seq):
                        # The whole pipeline — commit fence last —
                        # actually landed; the failure was response-side.
                        # Retrying would apply the deltas twice: suppress
                        # and account the rows as written.
                        self._faults.inc("dedup_suppressed_flushes")
                        self._seq_acked = self._last_attempt_seq
                        self._consec_failures = 0
                        self._on_written(payload, stamp)
                    else:
                        self._on_failure(payload.to_rows() if arrays
                                         else payload, e)
                else:
                    if fenced_out:
                        continue   # superseded epoch: dropped, not written
                    self._consec_failures = 0
                    if self._xo:
                        self._seq_acked = self._last_attempt_seq
                    # latency bookkeeping only for rows that actually landed
                    self._on_written(payload, stamp)
            finally:
                self._q.task_done()

    # -- exactly-once fence protocol -----------------------------------
    def _apply_fenced(self, rows: list, stamp: int, absolute: bool) -> bool:
        """One fenced apply: claim/verify the epoch, then rows + fence in
        one pipeline.  Returns False when a newer epoch owns the sink —
        this writer is a zombie (its engine was abandoned by a supervised
        restart) and the batch is DROPPED, never retained: the new
        lineage's ledger is the truth and stale deltas would corrupt it.
        Raises on sink errors like the plain path (rows then retained)."""
        import sys

        self._last_attempt_seq = None
        # The epoch is ONLY ever claimed engine-side (_xo_attach_sink),
        # never here: a writer claiming lazily at apply time could be a
        # zombie reading the fence AFTER its successor claimed — it
        # would "claim" an even newer epoch, fence out the LIVE writer,
        # and silently drop the live lineage's batches (the exact
        # undercount the 20-seed sweep caught).  The engine never
        # submits without a claimed epoch, so this is a bug trap.
        if self._epoch is None:
            raise RuntimeError(
                "fenced writer received a batch without a claimed epoch")
        e, _, _ = read_fence(self._redis, self._fence_key)
        if e > self._epoch:
            if not self._fenced:
                print(f"redis writer: fenced out (sink epoch {e} > "
                      f"writer epoch {self._epoch}); dropping "
                      f"{len(rows)} stale rows", file=sys.stderr,
                      flush=True)
            self._fenced = True
            self._faults.inc("fence_conflicts")
            return False
        self._seq += 1
        self._last_attempt_seq = self._seq
        write_windows_pipelined(
            self._redis, rows, time_updated=stamp, absolute=absolute,
            cache=self._uuid_cache,
            fence=(self._fence_key, self._epoch, self._seq))
        return True

    def _landed(self, seq: int | None) -> bool:
        """Did the flush with ``seq`` fully land despite the raised
        error?  True iff the sink's commit fence — the LAST command of
        that flush's pipeline — records exactly our (epoch, seq)."""
        if seq is None or self._epoch is None:
            return False
        try:
            e, s, _ = read_fence(self._redis, self._fence_key)
        except BaseException:
            return False    # sink still down: treat as not landed
        return e == self._epoch and s == seq

    def fence_state(self) -> tuple[int, int]:
        """(epoch, last fully-landed flush seq): what a snapshot records
        as the fence it covers.  Read after ``drain()`` for a stable
        value (the writer thread owns these fields)."""
        return (self._epoch or 0, self._seq_acked)

    def has_failed(self) -> bool:
        with self._lock:
            return bool(self._failed)

    def dirty_rows(self) -> int:
        """Retained failed-write rows awaiting reclaim (telemetry: the
        sink-health gauge — nonzero means the sink is/was down)."""
        with self._lock:
            return self._failed_rows

    def take_failed(self) -> list[list]:
        """Hand back batches whose write failed (clears the retention).
        The engine re-merges them into ``_pending`` so the next flush
        retries — a transient Redis outage must not undercount windows."""
        with self._lock:
            failed, self._failed = self._failed, []
            self._failed_rows = 0
        return failed

    def submit(self, rows, stamp: int | None,
               absolute: bool | None = None) -> None:
        """Queue one writeback payload.  ``absolute`` overrides the
        writer-level mode per payload (the exactly-once path mixes
        absolute ledger reconciles with plain delta batches); None keeps
        the constructor's mode."""
        self._q.put((rows, stamp, absolute))

    def drain(self) -> None:
        """Block until every submitted batch was attempted.  Failures are
        not raised here — they sit in ``take_failed`` for reclaim."""
        self._q.join()

    def close(self) -> None:
        """Stop the thread.  Raises if batches failed and were never
        reclaimed — silent data loss at shutdown is not an option.  The
        lost rows are ALSO counted (``rows_lost`` in FaultCounters)
        before raising: callers that survive the raise — or harnesses
        reading the fault map after the fact — still see the loss in the
        accounting, never only in a log line."""
        if self._thread.is_alive():
            self._q.put(None)
            self._wake.set()  # cut short any in-progress backoff sleep
            self._thread.join()
        with self._lock:
            lost, err = len(self._failed), self._error
            rows_lost = self._failed_rows
        if lost:
            self._faults.inc("rows_lost", rows_lost)
            raise RuntimeError(
                f"redis writer shut down with {lost} unwritten batches "
                f"({rows_lost} window rows lost)"
            ) from err


class AdAnalyticsEngine:
    """Exact per-(campaign, 10 s window) view counting — BASELINE config #1."""

    # Subclasses whose pending values are absolute snapshots (not deltas)
    # set this so the Redis writer HSETs instead of HINCRBYs.
    absolute_counts = False
    # Checkpoint compatibility class: restore refuses a snapshot from a
    # different family (engines with different device state would silently
    # misinterpret each other's arrays).  The sharded engine shares
    # "exact" with the base deliberately — its state is the same counts.
    ENGINE_FAMILY = "exact"

    def __init__(self, cfg: BenchmarkConfig, ad_to_campaign: dict[str, str],
                 campaigns: list[str] | None = None,
                 redis: RedisLike | None = None,
                 method: str | None = None,
                 input_format: str = "json"):
        self.cfg = cfg
        self.redis = redis
        self.divisor = cfg.jax_time_divisor_ms
        self.lateness = cfg.jax_allowed_lateness_ms

        def _new_encoder():
            """ONE construction+configuration site: the primary encoder
            and every pool worker must be configured identically."""
            e = make_encoder(ad_to_campaign, campaigns,
                             divisor_ms=self.divisor,
                             lateness_ms=self.lateness,
                             use_native=cfg.jax_use_native_encoder)
            if self.HASHED_IDS:
                e.set_hash_ids(True)
            elif not self.NEEDS_INTERNED_IDS:
                e.set_intern_ids(False)
            return e

        self.encoder = _new_encoder()
        self.join_table = jnp.asarray(self.encoder.join_table)
        self.W = cfg.jax_window_slots
        self.method = method or default_method(self.encoder.num_campaigns)
        self.batch_size = cfg.jax_batch_size
        self.scan_batches = max(cfg.jax_scan_batches, 1)
        self._encode = (self.encoder.encode if input_format == "json"
                        else self.encoder.encode_tbl)
        if self.W * self.divisor <= self.lateness + 2 * self.divisor:
            raise ValueError(
                f"window ring too small: {self.W} slots x {self.divisor} ms "
                f"must exceed lateness {self.lateness} ms + 2 windows")
        # Safe event-time span between device drains.
        self._span_guard = self.W * self.divisor - self.lateness - 2 * self.divisor
        self.state = wc.init_state(self.encoder.num_campaigns, self.W)

        # host-side bookkeeping
        self._span_start: int | None = None   # min unflushed event time (abs)
        # Host mirror of the device watermark (max absolute event time
        # folded): lets drains recompute the unflushed span WITHOUT a
        # blocking device pull (sketch engines whose open windows stay
        # on device need the oldest-possibly-open window after a drain).
        self._host_wm: int | None = None
        # Deferred drains: (deltas, window_ids) DEVICE arrays from
        # flush_deltas calls whose host materialization is postponed.  The
        # device executes enqueued programs in order, so the ring is safe
        # to reuse the moment flush_deltas is DISPATCHED; blocking on the
        # result (np.asarray) would stall the host behind every batch
        # queued before it — the round-2 bench lost 85% of its wall time
        # exactly there.  Materialization happens at flush()/snapshot()
        # time, when the 1 Hz cadence has let the queue drain naturally.
        # tagged parked drains:
        #   ("dense", deltas, wids)
        #   ("compact", idx, vals, nnz, dense_handle, wids)
        #   ("rows_compact", rows_np, idx, vals, nnz, sub_handle, wids)
        #   ("rows_host", rows_np, sub_np, wids)      [CPU zero-copy]
        # plus engine-specific tags absorbed by _materialize_custom
        # (e.g. ("hll", est, wids)).  When adding a tag with a dense
        # fallback handle, extend _park's async-copy skip table.
        self._undrained: list[tuple] = []
        # Drains parked one flush cycle ago whose device->host copies were
        # started asynchronously (tunneled accelerators: a blocking pull
        # costs ~150 ms fixed and, behind a backed-up transfer queue,
        # seconds — the round-5 TPU trace billed 5.5 s of a 30 s paced run
        # to exactly this).  flush() materializes THESE (data already
        # local) and rotates the fresh drains in behind them; full
        # materialization points (snapshot, final flush, catchup end)
        # drain both lists.
        self._undrained_ready: list[tuple] = []
        backend = jax.default_backend()
        defer_env = os.environ.get("STREAMBENCH_DEFER_DRAIN_PULL",
                                   "auto").strip().lower()
        self._defer_pull = (backend != "cpu" if defer_env in ("auto", "")
                            else defer_env not in ("0", "false", "off",
                                                   "no"))
        # Packed wire word (ops.windowcount.pack_columns): when the ad
        # space fits the 28-bit field AND either this class's device
        # hooks are the exact-count kernels (pure base) or the subclass
        # ships its own packed scan (e.g. the sharded engine).
        # Deliberately method-identity introspection, NOT an inherited
        # opt-in flag: a flag would silently stay True in a subclass
        # that overrides _device_scan with different columns (the
        # inheritance trap), while introspection fails CLOSED — a
        # subclass that overrides a device hook without shipping
        # _device_scan_packed falls back to unpacked transfers
        # (correct, just slower on tunneled backends; override
        # _device_scan_packed to reclaim the packed win).
        self._pack_ok = self.encoder.join_table.size < wc.PACK_AD_MAX
        self._packed_scan = self._pack_ok and (
            type(self)._device_scan_packed
            is not AdAnalyticsEngine._device_scan_packed
            or (type(self)._device_scan is AdAnalyticsEngine._device_scan
                and type(self)._device_step
                is AdAnalyticsEngine._device_step))
        # Dirty-campaign tracking (large key spaces only): per-batch
        # campaign sets accumulated host-side so a drain can gather just
        # the touched rows instead of walking C x W cells.
        self._join_np = self.encoder.join_table
        self._dirty_rows: list[np.ndarray] = []
        # pending Redis deltas: (campaign_idx, abs_window_ts) -> count
        # (dict = slow path for reclaims/snapshots; _pending_np = numpy
        # triples straight from drains, the hot path)
        self._pending: dict[tuple[int, int], int] = defaultdict(int)
        self._pending_np: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        # campaign-name table for the native store's index-form bulk
        # writeback; False = not yet resolved (resolution needs redis)
        self._camp_table = False
        self.events_processed = 0
        self.windows_written = 0
        self.started_ms = now_ms()
        self.last_event_ms = self.started_ms
        # fork-style latency accounting: abs_window_ts -> last time_updated
        self.window_latency: dict[int, int] = {}
        # stage spans (SURVEY.md §5.1) + Apex-style decile accounting (§5.5)
        self.tracer = Tracer()
        self.latency_tracker = LatencyTracker(window_ms=self.divisor)
        # fault/retry/recovery accounting (ROBUSTNESS.md): shared with the
        # writer thread; surfaced via RunStats.faults at end of run
        self.faults = FaultCounters()
        # exactly-once writeback (jax.sink.exactly_once, ROBUSTNESS.md
        # "Exactly-once") — ALL dormant when the flag is off:
        #   _sink_totals  cumulative per-window ledger of every delta
        #                 ever handed to the writer (the idempotent
        #                 absolute value a reconcile writes)
        #   _taint        windows whose last flush failed or may have
        #                 partially applied -> next flush rewrites them
        #                 ABSOLUTE from the ledger
        #   _reconcile_all  resumed over a sink holding unfenced flushes:
        #                 every flush this attempt writes absolute
        #   _xo_baseline  the restored snapshot's (epoch, seq) fence —
        #                 what the sink fence is compared against
        self._xo = bool(getattr(cfg, "jax_sink_exactly_once", False))
        self._fence_key = fence_key(cfg.kafka_topic)
        self._sink_totals: dict[tuple[int, int], int] = {}
        self._taint: set[tuple[int, int]] = set()
        self._reconcile_all = False
        self._xo_baseline: tuple[int, int] = (0, 0)
        self._xo_attached = not self._xo
        self._sink_epoch: int | None = None
        self._sink_seq0 = 0
        # live telemetry (obs/): None until attach_obs — the default
        # engine pays nothing for the observability layer beyond this
        # attribute and one None check per flush writeback.  The
        # lifecycle tracker (obs.lifecycle, per-window latency
        # attribution) is likewise None unless attach_obs opted in.
        self._obs_hist = None
        self._obs_lifecycle = None
        # measured device occupancy (obs.occupancy): None unless
        # attach_obs opted in — one None check per dispatch otherwise
        self._obs_occupancy = None
        # host->device transfer ledger (obs.xfer) + per-shard skew
        # tracker (sharded engines feed it via shard_stats kernels):
        # same contract — None and one check per dispatch until
        # attach_obs opts in
        self._obs_xfer = None
        self._obs_shard = None
        self._xfer_seen_buf = None     # devdecode buf attribution memo
        # bench/debug knob: force the separate-column wire format even
        # where the packed word is eligible, so the transfer ledger can
        # MEASURE both formats on the same journal (the bench xfer
        # probe and tests/test_xfer.py use it; engine output is
        # identical either way — the packed path is bit-equal by
        # construction and tested)
        if os.environ.get("STREAMBENCH_WIRE_FORMAT", "").strip().lower() \
                == "unpacked":
            self._pack_ok = False
            self._packed_scan = False
        self._writer: _RedisWriter | None = None
        # Parallel encode pool (multi-core hosts): per-thread encoders,
        # sound only for engines whose kernel never reads the interned
        # user/page columns (see encode.parallel).
        self._encode_pool = None
        if (cfg.jax_encode_workers > 1 and self.PARALLEL_ENCODE_OK
                and input_format == "json"
                and getattr(self.encoder, "RELEASES_GIL", False)):
            # GIL-bound (pure Python) encoders gain nothing from threads;
            # only the native encoder's ctypes scan parallelizes.
            from streambench_tpu.encode.parallel import ParallelEncodePool

            # the pool holds the factory; no reference is kept otherwise
            # (the closure pins ad_to_campaign, unnecessary pool-less)
            self._encode_pool = ParallelEncodePool(
                self.encoder, _new_encoder,
                workers=cfg.jax_encode_workers)
        # On-device event decode (ops.devdecode; jax.decode.device):
        # raw journal blocks ship to the device and bytes->columns +
        # view filter + ad->campaign hash join + window fold run inside
        # one jitted step; the host keeps only the layout probe.  None
        # whenever the mode is off or this engine/data shape is not
        # eligible — the host encoders stay the (byte-identical)
        # fallback, never a changed path.
        self._devdecode = None
        if input_format == "json":
            self._devdecode = self._maybe_device_decoder(
                getattr(cfg, "jax_decode_device", "off"))

    # Subclasses whose _device_step is not the exact-count kernel clear
    # this; process_chunk then folds per-batch (still with deferred
    # drains) instead of through the scanned exact kernel.
    SCAN_SUPPORTED = True
    # EncodedBatch columns the scanned kernel consumes, in _device_scan
    # argument order (sketch engines need e.g. user_idx).
    SCAN_COLUMNS = ("ad_idx", "event_type", "event_time", "valid")
    # Extra EncodedBatch columns a subclass's packed scan consumes
    # between the packed word and event_time (e.g. HLL's user ids).
    PACKED_EXTRA_COLS: tuple = ()
    # Engines whose kernel reads interned user/page columns must keep a
    # single consistent intern table and clear this (encode.parallel).
    PARALLEL_ENCODE_OK = True
    # Whether the device kernel reads the interned user/page columns.
    # When False, the encoder skips interning entirely (two hash probes
    # per row — the biggest per-event encode cost after tokenization).
    NEEDS_INTERNED_IDS = False
    # Stateless crc32 id columns instead of intern indices (wins over
    # NEEDS_INTERNED_IDS).  For kernels that only need a well-mixed
    # identity (HLL): consistent across pool workers and restarts, no
    # intern table in snapshots, parallel encode stays sound.
    HASHED_IDS = False
    # Invalid rows appended per batch at dispatch so a device mesh's
    # data axis divides B (the sharded engines set an instance value);
    # the transfer ledger scales its per-dispatch byte accounting by it
    # because the pad rows really do cross the host->device link.
    _data_pad = 0
    # Whether _device_step packs the wire word when _pack_ok (the base
    # exact engine does; sketch steps always ship separate columns) —
    # read by the transfer ledger's _xfer_step_cols, never the hot path
    STEP_PACKS = True

    # ------------------------------------------------------------------
    def _maybe_device_decoder(self, mode: str):
        """Build the device decoder when the mode and this engine allow
        it; None otherwise (callers treat None as "host encode").

        Eligibility fails CLOSED, like ``_packed_scan``: only the pure
        exact-count device hooks are decodable (a subclass overriding
        ``_device_step``/``_device_scan`` consumes columns this path
        never builds — sketch engines read user ids, the sharded engine
        reshards), the key space must stay under the dirty-row-drain
        threshold (those drains track touched campaigns from host-side
        ``ad_idx`` columns that no longer exist), and the ad table must
        be the generator's fixed 36-byte uuid wire format.  ``auto``
        additionally gates on the measured A/B
        (``devdecode.auto_enabled``)."""
        if mode == "off":
            return None
        if not (type(self)._device_step is AdAnalyticsEngine._device_step
                and type(self)._device_scan
                is AdAnalyticsEngine._device_scan):
            return None
        if self._track_dirty_rows():
            return None
        from streambench_tpu.ops import devdecode

        if mode == "auto" and not devdecode.auto_enabled():
            return None
        try:
            return devdecode.DeviceDecoder(
                self.encoder, batch_size=self.batch_size,
                scan_batches=self.scan_batches,
                divisor_ms=self.divisor, lateness_ms=self.lateness)
        except ValueError as e:
            if mode == "on":
                import sys

                print(f"device decode requested but unsupported here "
                      f"({e}); falling back to host encode",
                      file=sys.stderr, flush=True)
            return None

    # ------------------------------------------------------------------
    def warmup(self) -> None:
        """Compile every device program the ingest paths can dispatch —
        the single-batch step, each power-of-2 scan group size (the
        ``_fold_group`` padding buckets), and the drain — using an
        all-invalid batch, then block until done.

        Call once before measuring or serving: a cold XLA compile landing
        mid-run stalls this process for seconds, and on a single-core
        host it also starves every co-located process (the round-3 bench
        saw a paced producer pushed to ~1.5k ev/s by exactly this).
        Invalid rows are masked in every kernel, so state is semantically
        unchanged.
        """
        import jax as _jax

        zb = self._encode([], self.batch_size)
        with self.tracer.span("warmup"):
            self._device_step(zb)
            if self.SCAN_SUPPORTED and self.scan_batches > 1:
                sizes = []
                k = 2
                while k < self.scan_batches:
                    sizes.append(k)
                    k *= 2
                # _fold_group caps padding at scan_batches, so the largest
                # real shape is scan_batches itself (which is only a
                # power of two when the config says so).
                sizes.append(self.scan_batches)
                for k in sizes:
                    if self._packed_scan:
                        pk = wc.pack_columns(zb.ad_idx, zb.event_type,
                                             zb.valid)
                        cols = ([jnp.asarray(np.stack([pk] * k))]
                                + [jnp.asarray(np.stack(
                                    [getattr(zb, c)] * k))
                                   for c in self.PACKED_EXTRA_COLS]
                                + [jnp.asarray(np.stack(
                                    [zb.event_time] * k))])
                        self._device_scan_packed(*cols)
                    else:
                        cols = [jnp.asarray(np.stack([getattr(zb, c)] * k))
                                for c in self.SCAN_COLUMNS]
                        self._device_scan(*cols)
            self._drain_device()
            if self._track_dirty_rows():
                # compile the dirty-rows drain program too (a ~3 s XLA
                # compile at C=1e6 must not land mid-run); row 0 holds
                # zeros, so nothing materializes
                self._dirty_rows.append(np.zeros(1, np.int64))
                self._drain_device()
                # ... and the strategies an overflowing drain (touched
                # set > DIRTY_ROWS_CAP) falls through to — state is all
                # zero, so these are no-ops semantically
                if self._use_compact_drain():
                    *_, self.state = wc.flush_deltas_compact(
                        self.state, cap=self.COMPACT_DRAIN_CAP,
                        divisor_ms=self.divisor,
                        lateness_ms=self.lateness)
                else:
                    _, _, self.state = wc.flush_deltas(
                        self.state, divisor_ms=self.divisor,
                        lateness_ms=self.lateness)
            self._materialize_drains()
            _jax.block_until_ready(self.state)
        self._span_start = None

    # ------------------------------------------------------------------
    def process_lines(self, lines: list[bytes]) -> int:
        """Encode + fold up to one batch worth of lines.  Returns rows used."""
        for off in range(0, max(len(lines), 1), self.batch_size):
            chunk = lines[off:off + self.batch_size]
            if not chunk:
                break
            with self.tracer.span("encode"):
                batch = self._encode(chunk, self.batch_size)
            if batch.n == 0:
                continue
            self._fold(batch)
        return len(lines)

    def process_chunk(self, lines: list[bytes]) -> int:
        """Encode + fold up to ``scan_batches`` batches with ONE device
        dispatch (``lax.scan`` over stacked micro-batches).

        This is the dispatch-amortization path for catchup: per-batch
        enqueue overhead (~10 ms against a remote TPU backend) is paid
        once per K batches instead of once per batch.  Falls back to the
        per-batch path when the engine's kernel has no scanned form or
        the chunk's event-time span doesn't fit the ring in one piece.
        """
        self.fold_batches(self.encode_chunk_lines(lines))
        return len(lines)

    def encode_chunk_lines(self, lines: list[bytes]) -> list:
        """Encode-only half of ``process_chunk``: batch-sized slices
        through the encode pool (or the primary encoder), empty batches
        dropped.  The ingest pipeline's encode stage calls this from its
        own thread; nothing here touches device state."""
        if self._devdecode is not None and lines:
            # line-mode ingest with device decode: rejoin into one block
            # (a memcpy) so paced/streaming readers share the raw-bytes
            # path; poll() strips the newlines, so restore them
            return self._prepare_device_blocks(b"\n".join(lines) + b"\n")
        B = self.batch_size
        if self._encode_pool is not None:
            with self.tracer.span("encode"):
                encoded = self._encode_pool.encode_chunks(
                    [lines[off:off + B] for off in range(0, len(lines), B)],
                    B)
            batches = [b for b in encoded if b.n]
        else:
            batches = []
            for off in range(0, len(lines), B):
                with self.tracer.span("encode"):
                    b = self._encode(lines[off:off + B], B)
                if b.n:
                    batches.append(b)
        if self._obs_lifecycle is not None:
            self._obs_lifecycle.stamp_encoded(batches)
        return batches

    def fold_batches(self, batches: list) -> int:
        """Dispatch-only half of the ingest paths: fold already-encoded
        batches into device state IN ORDER (scan-grouped when the kernel
        supports it).  Returns parsed events folded.  The ingest
        pipeline's host loop calls this with batches its encode stage
        produced; the serial paths compose it with the encode halves.

        Device-decode items (``devdecode.PreparedBlock``) interleave
        with encoded batches in journal order: runs of encoded batches
        keep the scan-grouped path, prepared blocks dispatch through
        the fused decode+fold scan."""
        before = self.events_processed
        K = self.scan_batches
        run: list = []

        def flush_run() -> None:
            if not run:
                return
            if not self.SCAN_SUPPORTED or K <= 1:
                for b in run:
                    self._fold(b)
            else:
                for g in range(0, len(run), K):
                    self._fold_group(run[g:g + K])
            run.clear()

        for b in batches:
            if getattr(b, "is_device_block", False):
                flush_run()
                self._fold_prepared(b)
            else:
                run.append(b)
        flush_run()
        return self.events_processed - before

    def _fold_group(self, batches: list) -> None:
        """Fold up to ``scan_batches`` encoded batches in one dispatch."""
        if len(batches) == 1:
            self._fold(batches[0])
            return
        lo = min(int(b.event_time[:b.n].min()) + b.base_time_ms
                 for b in batches)
        hi = max(int(b.event_time[:b.n].max()) + b.base_time_ms
                 for b in batches)
        if hi - lo > self._span_guard:
            # The group alone outspans the ring; the per-batch path can
            # drain between batches and halve over-wide ones.
            for b in batches:
                self._fold(b)
            return
        if self._span_start is None:
            self._span_start = lo
        if hi - self._span_start > self._span_guard:
            with self.tracer.span("drain"):
                self._drain_device()
            # _drain_device may pin _span_start to an OLDER still-open
            # window (HLL keeps open-window registers on device); only
            # move it forward to the group minimum if that is older —
            # clobbering it would under-measure the unflushed span and
            # let a new window claim a still-open slot (same rule as
            # _fold).
            if self._span_start is None or lo < self._span_start:
                self._span_start = lo

        # Pad the stack to the next power-of-two group size so the scan
        # compiles once per bucket (log2(K)+1 shapes, not one per group
        # size) while partial groups don't pay for a full K of padding.
        # All-invalid padding batches are no-ops in the kernel (masked
        # everywhere, the watermark max treats invalid rows as -inf).
        k = 1
        while k < len(batches):
            k *= 2
        pad = min(k, self.scan_batches) - len(batches)
        if self._track_dirty_rows():
            self._note_batch_campaigns(batches)
        if self._packed_scan:
            # One packed word (+ any engine extras, e.g. HLL's user ids)
            # + time per event instead of four-to-five buffers: a
            # packed-zero pad row decodes to (ad 0, type -1,
            # valid False) — masked everywhere.
            packs = [wc.pack_columns(b.ad_idx, b.event_type, b.valid)
                     for b in batches]
            extras = [[getattr(b, c) for b in batches]
                      for c in self.PACKED_EXTRA_COLS]
            times = [b.event_time for b in batches]
            if pad:
                packs += [np.zeros_like(packs[0])] * pad
                for arrs in extras:
                    arrs += [np.zeros_like(arrs[0])] * pad
                times += [np.zeros_like(times[0])] * pad
            stacks = ([np.stack(packs)]
                      + [np.stack(a) for a in extras]
                      + [np.stack(times)])
            cols = [jnp.asarray(s) for s in stacks]
            with self.tracer.span("device_scan"):
                self._device_scan_packed(*cols)
        else:
            stacks = []
            for name in self.SCAN_COLUMNS:
                arrs = [getattr(b, name) for b in batches]
                if pad:
                    arrs += [np.zeros_like(arrs[0])] * pad
                stacks.append(np.stack(arrs))
            cols = [jnp.asarray(s) for s in stacks]
            with self.tracer.span("device_scan"):
                self._device_scan(*cols)
        if self._obs_occupancy is not None:
            self._obs_occupancy.note_dispatch(self.state)
        if self._obs_xfer is not None:
            # the numpy stacks ARE the dispatched host payload; the
            # trailing axis is the per-batch row count the mesh pad
            # scales
            self._note_xfer(
                "packed" if self._packed_scan else "unpacked",
                sum(b.n for b in batches), stacks, stacks[0].shape[-1])
        for b in batches:
            self._note_watermark(b)
        self.events_processed += sum(b.n for b in batches)
        self.last_event_ms = now_ms()

    def _fold_prepared(self, pb) -> None:
        """Ring-guarded fold of one device-decode block: the same two
        span hazards as ``_fold`` (drain when the unflushed span would
        overrun; halve when the block ALONE outspans the ring), then one
        fused decode+fold dispatch.  Host bookkeeping (watermark mirror,
        attribution, event counting) reads the probe's times through the
        block's EncodedBatch-shaped surface."""
        if pb.n == 0:
            return
        vt = pb.event_time
        batch_max = int(vt.max()) + pb.base_time_ms
        batch_min = int(vt.min()) + pb.base_time_ms
        if batch_max - batch_min > self._span_guard and pb.n > 1:
            for half in pb.halves():
                self._fold_prepared(half)
            return
        if self._span_start is None:
            self._span_start = batch_min
        if batch_max - self._span_start > self._span_guard:
            with self.tracer.span("drain"):
                self._drain_device()
            if self._span_start is None or batch_min < self._span_start:
                self._span_start = batch_min
        with self.tracer.span("device_decode"):
            self.state = self._devdecode.fold(self.state, pb,
                                              method=self.method)
        if self._obs_occupancy is not None:
            self._obs_occupancy.note_dispatch(self.state)
        if self._obs_xfer is not None:
            # the raw byte buffer crossed at prepare() (device_put once
            # per block); attribute it to the FIRST fold that uses it —
            # span-guard halves share it — plus each fold's row vectors
            wire = pb.starts.nbytes + pb.lens.nbytes
            if id(pb.buf_dev) != self._xfer_seen_buf:
                self._xfer_seen_buf = id(pb.buf_dev)
                wire += int(pb.buf_dev.nbytes)
            self._obs_xfer.note_dispatch("devdecode", pb.n, wire)
        self._note_watermark(pb)
        self.events_processed += pb.n
        self.last_event_ms = now_ms()

    def _device_scan(self, ad_idx, event_type, event_time, valid) -> None:
        """Fold ``[K, B]`` stacked batches in one compiled scan."""
        self.state = wc.scan_steps(
            self.state, self.join_table, ad_idx, event_type, event_time,
            valid, divisor_ms=self.divisor, lateness_ms=self.lateness,
            method=self.method)

    def _device_scan_packed(self, packed, event_time) -> None:
        """``_device_scan`` over the packed wire word (half the transfer
        bytes, two buffers instead of four — see
        ``ops.windowcount.pack_columns``)."""
        self.state = wc.scan_steps_packed(
            self.state, self.join_table, packed, event_time,
            divisor_ms=self.divisor, lateness_ms=self.lateness,
            method=self.method)

    # ------------------------------------------------------------------
    @property
    def supports_block_ingest(self) -> bool:
        """True when raw journal blocks can be encoded without per-line
        Python objects (native encoder + JSON wire format, or the
        device-decode path — which wants raw bytes by construction).
        Sketch engines with a Python-pinned encoder inherit False.  With
        a parallel encode pool the block is carved at record boundaries
        first and parsed on all workers (``carve_block_parallel``), so
        block ingest and multi-core encoding compose — the round-3
        either/or (pool XOR block mode) left the fastest ingest path
        single-threaded."""
        if self._devdecode is not None:
            return True
        return (hasattr(self.encoder, "encode_block")
                and self._encode == self.encoder.encode)

    def process_block(self, data: bytes) -> int:
        """Ingest one raw journal block (complete newline-delimited
        records, from ``JournalReader.poll_block``).  Returns parsed
        events folded.

        The zero-copy fast path: the native scanner finds record
        boundaries and parses in one pass, so the per-line split/join
        round trip (~45% of ingest cost at line rate) never happens.
        """
        if not data:
            return 0
        return self.fold_batches(self.encode_raw_block(data))

    def encode_raw_block(self, data: bytes) -> list:
        """Encode-only half of ``process_block``: carve + parse one raw
        journal block into ``EncodedBatch`` groups without folding (the
        ingest pipeline's encode stage).  Engines without block ingest
        fall back to splitting lines through ``encode_chunk_lines``, so
        both ingest modes see identical events."""
        if not data:
            return []
        if self._devdecode is not None:
            return self._prepare_device_blocks(data)
        if not self.supports_block_ingest:
            lines = data.split(b"\n")
            if lines and not lines[-1]:
                lines.pop()
            return self.encode_chunk_lines(lines)
        B = self.batch_size
        with self.tracer.span("encode"):
            if self._encode_pool is not None:
                batches, start = self._encode_pool.carve_block_parallel(
                    data, B)
            else:
                batches, start = self.encoder.carve_block(data, B)
            if start < len(data):
                # unterminated trailing record (poll_block never produces
                # one, but direct callers can): parse it as one line so
                # both process_block branches see identical events
                b = self._encode([data[start:]], B)
                if b.n:
                    batches.append(b)
        if self._obs_lifecycle is not None:
            self._obs_lifecycle.stamp_encoded(batches)
        return batches

    def _prepare_device_blocks(self, data: bytes) -> list:
        """Device-decode "encode" stage: probe the raw block (record
        boundaries + fixed-layout validation + times, NO columns) and
        return dispatch-ready items — probe-rejected rows re-encoded
        through the host encoder first (bad-line counting + dead-letter
        parity), then the :class:`devdecode.PreparedBlock`\\ s.  The
        fallback batches fold before the device rows of the same call,
        so a malformed row is never judged against a watermark its own
        block advanced."""
        with self.tracer.span("decode_probe"):
            blocks, bad_lines = self._devdecode.prepare(data)
            nl_end = data.rfind(b"\n") + 1
            if nl_end < len(data):
                # unterminated trailing record (poll_block never produces
                # one, but direct callers can): same one-line rule as the
                # host block path
                bad_lines.append(data[nl_end:])
        out: list = []
        if bad_lines:
            B = self.batch_size
            for off in range(0, len(bad_lines), B):
                with self.tracer.span("encode"):
                    b = self._encode(bad_lines[off:off + B], B)
                if b.n:
                    out.append(b)
        out.extend(blocks)
        if self._obs_lifecycle is not None:
            self._obs_lifecycle.stamp_encoded(out)
        return out

    def _fold(self, batch) -> None:
        """Ring-guarded fold of one encoded batch, splitting when needed.

        Two span hazards can corrupt the ring: (a) the batch stretches the
        *unflushed* span past the safe limit -> drain first; (b) the batch
        ALONE spans more event time than the ring can hold (sparse or
        low-rate streams: batch_size x inter-event gap > ring span) -> no
        drain can help; halve and recurse.  Halving keeps the jit shape
        set bounded (log2(B) distinct shapes, each compiled once).
        """
        vt = batch.event_time[:batch.n]
        batch_max = int(vt.max()) + batch.base_time_ms
        batch_min = int(vt.min()) + batch.base_time_ms
        if batch_max - batch_min > self._span_guard and batch.n > 1:
            for half in self._halves(batch):
                if half.n:
                    self._fold(half)
            return
        if self._span_start is None:
            self._span_start = batch_min
        # Ring-reuse guard: drain device deltas BEFORE this batch if its
        # max would stretch the unflushed span past the safe limit.
        if batch_max - self._span_start > self._span_guard:
            with self.tracer.span("drain"):
                self._drain_device()
            if self._span_start is None or batch_min < self._span_start:
                self._span_start = batch_min
        if self._track_dirty_rows():
            self._note_batch_campaigns([batch])
        with self.tracer.span("device_step"):
            # async dispatch: the span covers transfer + enqueue, not
            # device completion (that overlaps the next encode — the
            # pipeline-parallel analog, SURVEY.md §2)
            self._device_step(batch)
        if self._obs_occupancy is not None:
            self._obs_occupancy.note_dispatch(self.state)
        if self._obs_xfer is not None:
            fmt, cols = self._xfer_step_cols(batch)
            self._note_xfer(fmt, batch.n, cols, batch.batch_size)
        self._note_watermark(batch)
        self.events_processed += batch.n
        self.last_event_ms = now_ms()

    def _note_watermark(self, batch) -> None:
        """Advance the host watermark mirror — strictly AFTER the fold
        that carries these events is dispatched, and over VALID rows
        only, so ``_host_wm`` equals the device watermark at every
        drain point (device programs execute in dispatch order).
        Updating before dispatch let the host run ahead of the device
        and a drain's span recompute treat still-open ring slots as
        closed."""
        if self._obs_lifecycle is not None:
            # attribution hook (obs.lifecycle): this batch's windows
            # just folded — record its read/encode stamps + fold time
            self._obs_lifecycle.note_fold(batch)
        v = batch.valid[:batch.n]
        if not v.any():
            return
        vt = batch.event_time[:batch.n]
        mx = int(vt.max() if v.all() else vt[v].max()) + batch.base_time_ms
        if self._host_wm is None or mx > self._host_wm:
            self._host_wm = mx

    # ------------------------------------------------------------------
    # host->device transfer accounting (obs.xfer) — called only when
    # attach_obs handed over a TransferLedger; never on the default path
    def _xfer_step_cols(self, batch):
        """``(fmt, cols)`` describing what ``_device_step`` ships for
        one batch: the column buffers at their wire dtypes, with
        ``batch.ad_idx`` standing in for the packed word (same int32
        ``[B]`` shape).  Mirrors the base step's packing decision;
        engines whose step never packs (single-device sketches)
        override — the introspection rule ``_packed_scan`` applies to
        the scan path only."""
        if self._pack_ok and self.STEP_PACKS:
            return "packed", ([batch.ad_idx]
                              + [getattr(batch, c)
                                 for c in self.PACKED_EXTRA_COLS]
                              + [batch.event_time])
        return "unpacked", [getattr(batch, c) for c in self.SCAN_COLUMNS]

    def _note_xfer(self, fmt: str, events: int, cols, rows: int) -> None:
        """Account one dispatch's payload: exact wire bytes from the
        dispatched buffers' dtypes (trailing axis = ``rows`` data rows,
        scaled by the mesh data-axis pad), int32-normalized column
        bytes alongside (see obs.xfer).  ``cols`` double as the timed
        device_put sample payload."""
        pad = self._data_pad
        wire = sum((c.nbytes // rows) * (rows + pad) for c in cols)
        colb = sum((c.size // rows) * (rows + pad) * 4 for c in cols)
        self._obs_xfer.note_dispatch(fmt, events, wire, colb,
                                     sample_arrays=cols)

    # ------------------------------------------------------------------
    # device-memory accounting (obs.devmem) — analysis-time only; each
    # entry costs one out-of-line compile (lower().compile() does not
    # share the jit call cache), so this runs once post-warmup
    def _devmem_kernels(self) -> list:
        """``(name, jitted_fn, args, statics)`` for the device programs
        this engine dispatches, built from an all-invalid batch (the
        warmup shapes).  Fails CLOSED like ``_packed_scan`` /
        ``_maybe_device_decoder``: a subclass that overrides the device
        hooks dispatches programs this base list cannot describe, so it
        returns [] unless the subclass ships its own list — the
        memory report then carries state + census only, never a wrong
        kernel table."""
        if not (type(self)._device_step is AdAnalyticsEngine._device_step
                and type(self)._device_scan
                is AdAnalyticsEngine._device_scan):
            return []
        zb = self._encode([], self.batch_size)
        statics = dict(divisor_ms=self.divisor, lateness_ms=self.lateness,
                       method=self.method)
        out: list = []
        if self._pack_ok:
            pk = wc.pack_columns(zb.ad_idx, zb.event_type, zb.valid)
            out.append(("step_packed", wc.step_packed,
                        (self.state, self.join_table, jnp.asarray(pk),
                         jnp.asarray(zb.event_time)), statics))
        else:
            out.append(("step", wc.step,
                        (self.state, self.join_table,
                         jnp.asarray(zb.ad_idx),
                         jnp.asarray(zb.event_type),
                         jnp.asarray(zb.event_time),
                         jnp.asarray(zb.valid)), statics))
        if self.SCAN_SUPPORTED and self.scan_batches > 1:
            K = self.scan_batches
            if self._packed_scan:
                pk = wc.pack_columns(zb.ad_idx, zb.event_type, zb.valid)
                out.append(("scan_packed", wc.scan_steps_packed,
                            (self.state, self.join_table,
                             jnp.asarray(np.stack([pk] * K)),
                             jnp.asarray(np.stack([zb.event_time] * K))),
                            statics))
            else:
                cols = tuple(jnp.asarray(np.stack([getattr(zb, c)] * K))
                             for c in self.SCAN_COLUMNS)
                out.append(("scan", wc.scan_steps,
                            (self.state, self.join_table) + cols,
                            statics))
        out.append(("drain", wc.flush_deltas, (self.state,),
                    dict(divisor_ms=self.divisor,
                         lateness_ms=self.lateness)))
        return out

    @staticmethod
    def _halves(batch):
        """Split an encoded batch into two fixed-shape halves (valid rows
        are compacted to the front, so column slices stay consistent)."""
        import dataclasses

        B = batch.batch_size
        B0 = B // 2
        n0 = min(batch.n, B0)
        cols = ("ad_idx", "event_type", "event_time", "user_idx",
                "page_idx", "ad_type", "valid")
        lo = dataclasses.replace(
            batch, **{c: getattr(batch, c)[:B0] for c in cols}, n=n0)
        hi = dataclasses.replace(
            batch, **{c: getattr(batch, c)[B0:] for c in cols},
            n=batch.n - n0)
        return lo, hi

    # ------------------------------------------------------------------
    def _device_step(self, batch) -> None:
        """Fold one ``EncodedBatch`` into device state (subclass hook:
        the sharded engine swaps in the mesh version; sketch engines use
        additional columns like ``user_idx``)."""
        if self._pack_ok:
            packed = wc.pack_columns(batch.ad_idx, batch.event_type,
                                     batch.valid)
            self.state = wc.step_packed(
                self.state, self.join_table, jnp.asarray(packed),
                jnp.asarray(batch.event_time),
                divisor_ms=self.divisor, lateness_ms=self.lateness,
                method=self.method)
            return
        self.state = wc.step(
            self.state, self.join_table,
            jnp.asarray(batch.ad_idx), jnp.asarray(batch.event_type),
            jnp.asarray(batch.event_time), jnp.asarray(batch.valid),
            divisor_ms=self.divisor, lateness_ms=self.lateness,
            method=self.method)

    # ------------------------------------------------------------------
    # Drain strategy at large key spaces (cells = C x W past the
    # threshold).  Preferred: host-tracked dirty campaign rows — the
    # drain gathers [touched, W] on device, so its cost scales with what
    # the stream actually wrote since the last drain (measured at
    # C=1e6, W=64 with 50k dirty cells on CPU: rows ~10 ms vs dense
    # walk ~680 ms vs on-device nonzero compaction ~3.4 s).  Fallbacks:
    # on-device compaction (accelerators only — the same measurement
    # shows XLA's sized-nonzero over the full cell space is SLOWER than
    # the dense host walk on CPU) when the touched set overflows the
    # cap, else the dense walk.
    COMPACT_DRAIN_MIN_CELLS = 1 << 22
    COMPACT_DRAIN_CAP = 1 << 18
    DIRTY_ROWS_CAP = 1 << 17

    def _use_compact_drain(self) -> bool:
        cells = self.state.counts.shape[0] * self.state.counts.shape[1]
        return (cells >= self.COMPACT_DRAIN_MIN_CELLS
                and jax.default_backend() != "cpu")

    def _track_dirty_rows(self) -> bool:
        counts = getattr(self.state, "counts", None)
        if counts is None:  # sketch states keep no dense [C, W] block
            return False
        return (counts.shape[0] * counts.shape[1]
                >= self.COMPACT_DRAIN_MIN_CELLS)

    def _note_batch_campaigns(self, batches) -> None:
        """Record which campaign rows the given encoded batches touch
        (hot path at large C only; ~100 us per 8k batch).  Over-
        inclusion is harmless — rows drain as zero — so invalid rows
        inside [:n] need no masking beyond the join-miss filter."""
        parts = []
        for b in batches:
            c = self._join_np[b.ad_idx[:b.n]]
            parts.append(c[c >= 0])
        if parts:
            self._dirty_rows.append(
                np.unique(np.concatenate(parts))
                if len(parts) > 1 else np.unique(parts[0]))

    def _drain_device(self) -> None:
        """Zero the device deltas for ring reuse; materialization deferred.

        Only *dispatches* the flush program — device programs execute in
        dispatch order, so the ring is reusable immediately; the returned
        arrays are parked in ``_undrained`` and pulled to the host in
        ``_materialize_drains`` (never on the hot path).
        """
        if self._track_dirty_rows():
            rows = (np.unique(np.concatenate(self._dirty_rows))
                    if len(self._dirty_rows) > 1
                    else (self._dirty_rows[0] if self._dirty_rows
                          else np.empty(0, np.int64)))
            self._dirty_rows = []
            if rows.size == 0:
                # nothing written since the last drain: counts are
                # already zero, only closed slots need freeing
                self.state = wc.flush_free_slots(
                    self.state, divisor_ms=self.divisor,
                    lateness_ms=self.lateness)
                self._span_start = None
                return
            if rows.size <= self.DIRTY_ROWS_CAP:
                # ONE fixed scatter/gather size: at C=1e6 each distinct
                # shape costs a ~3 s XLA compile on a small host, so
                # bucketing by size would scatter compiles through the
                # run
                R = min(self.DIRTY_ROWS_CAP,
                        self.state.counts.shape[0])
                padded = np.zeros(R, np.int32)
                padded[:rows.size] = rows
                if jax.default_backend() == "cpu":
                    # counts live in host memory: read the touched rows
                    # through the zero-copy view (13x faster than XLA's
                    # row gather), then only the in-place zero runs on
                    # device.  The fancy-index COPIES before the zero
                    # program is dispatched, so donation is safe.
                    view = np.asarray(self.state.counts)
                    sub_np = view[rows]
                    del view
                    wids, self.state = wc.flush_rows_zero(
                        self.state, jnp.asarray(padded),
                        divisor_ms=self.divisor,
                        lateness_ms=self.lateness)
                    self._park(("rows_host", rows, sub_np, wids))
                else:
                    # Accelerators: compact the gathered rows ON DEVICE
                    # — the padded-row pull is CAP-sized (33 MB at
                    # [131072, 64]) and the full-space compaction scans
                    # C x W cells; this scans R x W and pulls ~1 MB.
                    idx, vals, nnz, sub, wids, self.state = \
                        wc.flush_deltas_rows_compact(
                            self.state, jnp.asarray(padded),
                            jnp.int32(rows.size),
                            cap=self.COMPACT_DRAIN_CAP,
                            divisor_ms=self.divisor,
                            lateness_ms=self.lateness)
                    self._park(("rows_compact", rows, idx, vals, nnz,
                                sub, wids))
                self._span_start = None
                return
            # touched set overflowed the cap: fall through to the full-
            # space strategies below
        if self._use_compact_drain():
            idx, vals, nnz, dense, wids, self.state = \
                wc.flush_deltas_compact(
                    self.state, cap=self.COMPACT_DRAIN_CAP,
                    divisor_ms=self.divisor, lateness_ms=self.lateness)
            self._park(("compact", idx, vals, nnz, dense, wids))
        else:
            deltas, wids, self.state = wc.flush_deltas(
                self.state, divisor_ms=self.divisor,
                lateness_ms=self.lateness)
            self._park(("dense", deltas, wids))
        self._span_start = None

    def _park(self, parked: tuple) -> None:
        """Park a drain's device handles; on non-CPU backends also start
        their device->host copies NOW, so a later materialization finds
        the data already local instead of paying a blocking tunnel pull
        (~150 ms fixed, seconds behind a backed-up transfer queue)."""
        if self._defer_pull:
            # The compact/rows_compact tuples carry a dense fallback
            # handle ([C, W] counts / the gathered [R, W] block), read
            # only in the rare nnz-overflow case — async-copying it
            # would occupy the tunnel with 16-33 MB per drain that is
            # almost always discarded.
            skip = {"compact": {4}, "rows_compact": {5}}.get(
                parked[0], set())
            for i, x in enumerate(parked):
                if i in skip:
                    continue
                copy = getattr(x, "copy_to_host_async", None)
                if copy is not None:
                    try:
                        copy()
                    except Exception:
                        pass  # backend without async copies: pull blocks
        self._undrained.append(parked)

    def _materialize_drains(self, ready_only: bool = False) -> None:
        """Merge parked drain results into the host pending buffers.

        Stays in numpy: the (campaign, window, count) triples land in
        ``_pending_np`` as arrays (at catchup flush sizes a per-cell
        Python dict loop costs ~1.4 us x 10^5 cells per flush).  The
        ``_pending`` dict remains the slow-path buffer for reclaimed
        failed writes; ``_fold_pending_arrays`` merges the two views
        whenever dict semantics are required (snapshots).

        ``ready_only`` materializes just the drains whose async host
        copies were started at least a flush cycle ago
        (``_undrained_ready``); their data has had a full flush
        interval to stream back, so the pull is (measured) ~0.2 ms
        instead of ~90 ms blocking.  A readiness gate (``is_ready``)
        was tried and reverted: on the tunneled axon backend
        ``is_ready`` reports False after ``copy_to_host_async`` even
        once the data has landed, so gating starved every drain to its
        age cap and added seconds of write latency.  The default
        (``ready_only=False``) drains everything, in dispatch order.
        """
        if ready_only:
            parked_list = self._undrained_ready
            self._undrained_ready = []
        else:
            parked_list = self._undrained_ready + self._undrained
            self._undrained_ready = []
            self._undrained = []
        if not parked_list:
            return
        base = self.encoder.base_time_ms or 0
        W = self.W
        for parked in parked_list:
            if parked[0] == "rows_host":
                _, rows_np, sub, wids_d = parked
                wids = np.asarray(wids_d)
                ci_l, si = np.nonzero(sub)
                vals = sub[ci_l, si]
                ci = rows_np[ci_l]
            elif parked[0] == "compact":
                _, idx_d, vals_d, nnz_d, dense_d, wids_d = parked
                wids = np.asarray(wids_d)
                ci, si, vals = self._decode_compact(
                    idx_d, vals_d, nnz_d, lambda: np.asarray(dense_d))
            elif parked[0] == "rows_compact":
                _, rows_np, idx_d, vals_d, nnz_d, sub_d, wids_d = parked
                wids = np.asarray(wids_d)
                ci_l, si, vals = self._decode_compact(
                    idx_d, vals_d, nnz_d,
                    lambda: np.asarray(sub_d)[:rows_np.size])
                ci = rows_np[ci_l]
            elif parked[0] == "dense":
                _, deltas_d, wids_d = parked
                deltas = np.asarray(deltas_d)
                wids = np.asarray(wids_d)
                ci, si = np.nonzero(deltas)
                vals = deltas[ci, si]
            else:
                # engine-specific parked drain (e.g. the HLL estimate
                # block): the subclass absorbs it into its own pending
                # form, still in dispatch order
                self._materialize_custom(parked)
                continue
            if ci.size == 0:
                continue
            wid = wids[si]
            keep = wid >= 0
            if not keep.all():
                ci, wid, vals = ci[keep], wid[keep], vals[keep]
            if ci.size:
                self._pending_np.append(
                    (ci.astype(np.int64),
                     base + wid.astype(np.int64) * self.divisor,
                     vals.astype(np.int64)))

    def _materialize_custom(self, parked: tuple) -> None:
        """Hook for subclasses that park drains under their own tag
        (see ``_materialize_drains``); the base engine parks none."""
        raise ValueError(f"unknown parked drain tag {parked[0]!r}")

    def _decode_compact(self, idx_d, vals_d, nnz_d, fallback):
        """Decode one cap-compacted drain: ``(row_idx, slot, vals)``
        from the (idx, vals) pairs, or — when ``nnz`` overflowed the
        cap and the pairs are incomplete — from the dense 2-D block
        ``fallback()`` materializes.  The ONE copy of the overflow
        protocol for both the full-space and touched-rows drains."""
        nnz = int(nnz_d)
        if nnz <= self.COMPACT_DRAIN_CAP:
            idx = np.asarray(idx_d)[:nnz].astype(np.int64)
            vals = np.asarray(vals_d)[:nnz]
            ci, si = np.divmod(idx, self.W)
            return ci, si, vals
        dense = fallback()
        ci, si = np.nonzero(dense)
        return ci, si, dense[ci, si]

    def _oldest_open_span_start(self) -> int | None:
        """Absolute event time of the oldest window that could still be
        open, from the HOST-tracked watermark (no device pull): a window
        starting at ``ws`` is closed once ``ws + divisor + lateness <=
        watermark``.  Conservative by construction — it may point at a
        window that already closed (slightly earlier drains), never past
        one that is still open."""
        if self._host_wm is None:
            return None
        base = self.encoder.base_time_ms or 0
        min_open_wid = (self._host_wm - base - self.lateness) // self.divisor
        if min_open_wid < 0:
            min_open_wid = 0
        return base + min_open_wid * self.divisor

    def _fold_pending_arrays(self) -> None:
        """Merge ``_pending_np`` array triples into the ``_pending`` dict
        (snapshot/restore need the dict view; never on the hot path).
        Absolute engines (HLL) REPLACE — list order preserves recency,
        so the freshest estimate for a cell wins, matching write order."""
        for ci, ts, cnt in self._pending_np:
            if self.absolute_counts:
                for c, t, n in zip(ci.tolist(), ts.tolist(), cnt.tolist()):
                    self._pending[(c, t)] = n
            else:
                for c, t, n in zip(ci.tolist(), ts.tolist(), cnt.tolist()):
                    self._pending[(c, t)] += n
        self._pending_np.clear()

    def pending_counts(self) -> dict[tuple[int, int], int]:
        """Materialized-but-unflushed deltas as one dict view —
        ``(campaign_idx, abs_window_ts) -> count`` — folding the numpy
        drain triples in.  The supported inspection surface for tests
        and diagnostics (``_pending`` alone misses parked arrays)."""
        self._fold_pending_arrays()
        return dict(self._pending)

    def flush(self, time_updated: int | None = None, *,
              final: bool = False) -> int:
        """Drain device + write all pending deltas to Redis.

        Stamps ``time_updated`` at actual write time (``core.clj:149``
        defines latency truth as ``time_updated − window_ts``).  Returns
        window rows written.

        On tunneled accelerator backends a periodic (non-``final``)
        flush materializes only the drains parked LAST cycle — their
        async host copies have had a full flush interval to stream back
        — and rotates this cycle's drains in behind them.  That bounds
        the added write latency by one flush interval while removing the
        blocking tunnel pull (~150 ms fixed, seconds when the transfer
        queue is backed up) from the ingest loop.  ``final=True`` (end
        of run, close, snapshots) drains everything.
        """
        with self.tracer.span("drain"):
            self._drain_device()
            if self._defer_pull and not final:
                self._materialize_drains(ready_only=True)
                self._undrained_ready += self._undrained
                self._undrained = []
            else:
                self._materialize_drains()
        self._reclaim_failed_writes()
        if self._xo:
            return self._flush_exactly_once(time_updated)
        if not self._pending and not self._pending_np:
            return 0
        campaigns = self.encoder.campaigns
        rows = [(campaigns[c], ts, n)
                for (c, ts), n in self._pending.items()]
        self._pending.clear()
        # Drain triples stay numpy end-to-end when the sink is the native
        # store; otherwise they expand to rows here.  Duplicates across
        # drains are fine (HINCRBY accumulates; for absolute engines the
        # later, fresher value wins because write order is preserved —
        # rows, i.e. stale reclaims, are always submitted first).
        if self.absolute_counts and len(self._pending_np) > 1:
            # Several drains between flushes re-estimate the same
            # open-window cells; only the FRESHEST absolute value should
            # be written (the old dict path collapsed these — keep that
            # write volume without the per-cell dict cost).
            ci = np.concatenate([t[0] for t in self._pending_np])
            ts_a = np.concatenate([t[1] for t in self._pending_np])
            cnt = np.concatenate([t[2] for t in self._pending_np])
            order = np.lexsort((np.arange(len(ci)), ts_a, ci))
            ci_s, ts_s = ci[order], ts_a[order]
            last = np.concatenate(
                [(ci_s[1:] != ci_s[:-1]) | (ts_s[1:] != ts_s[:-1]),
                 [True]])
            keep = np.sort(order[last])  # freshest per cell, stable order
            self._pending_np = [(ci[keep], ts_a[keep], cnt[keep])]
        arrays = None
        table = self._native_table()
        if table is not None and self._pending_np:
            tri = self._pending_np
            ci = (tri[0][0] if len(tri) == 1
                  else np.concatenate([t[0] for t in tri]))
            ts_a = (tri[0][1] if len(tri) == 1
                    else np.concatenate([t[1] for t in tri]))
            cnt = (tri[0][2] if len(tri) == 1
                   else np.concatenate([t[2] for t in tri]))
            arrays = _ArrayRows(ci.astype(np.int32), ts_a, cnt, table,
                                campaigns)
        else:
            for ci, ts_a, cnt in self._pending_np:
                rows.extend(zip((campaigns[c] for c in ci.tolist()),
                                ts_a.tolist(), cnt.tolist()))
        self._pending_np.clear()
        if self._obs_lifecycle is not None:
            # attribution hook: these windows' rows are leaving for the
            # sink writer NOW — everything before this stamp is device/
            # pending residency (flush_ms), everything after is sink_ms
            ts_out = [ts for _, ts, _ in rows]
            if arrays is not None:
                ts_out.extend(np.unique(arrays.ts).tolist())
            self._obs_lifecycle.note_flush(ts_out)
        total = len(rows) + (len(arrays) if arrays is not None else 0)
        if self.redis is not None:
            writer = self._ensure_writer()
            if rows:
                writer.submit(rows, time_updated)
            if arrays is not None:
                writer.submit(arrays, time_updated)
        else:
            stamp = now_ms() if time_updated is None else time_updated
            if rows:
                self._note_written(rows, stamp)
            if arrays is not None:
                self._note_written(arrays, stamp)
        return total

    def _ensure_writer(self) -> _RedisWriter:
        """Get-or-start the background writeback thread (one per engine
        lifetime).  In exactly-once mode it inherits whatever epoch/seq
        the sink attach already claimed; with nothing claimed yet the
        writer claims lazily at its first apply."""
        if self._writer is None:
            self._writer = _RedisWriter(
                self.redis, self.absolute_counts, self.tracer,
                self._note_written, faults=self.faults,
                retry_base_ms=self.cfg.jax_sink_retry_base_ms,
                retry_cap_ms=self.cfg.jax_sink_retry_cap_ms,
                dirty_cap_rows=self.cfg.jax_sink_dirty_cap_rows,
                exactly_once=self._xo, fence_key=self._fence_key,
                epoch=self._sink_epoch, start_seq=self._sink_seq0)
        return self._writer

    # ------------------------------------------------------------------
    # exactly-once writeback (jax.sink.exactly_once; ROBUSTNESS.md
    # "Exactly-once")
    def _xo_attach_sink(self) -> None:
        """First fenced flush of an attempt: read the sink fence, detect
        unfenced flushes from a previous lineage, claim the next writer
        epoch.

        Detection: ``sink_seq > snapshot_seq`` means whole flushes landed
        after the snapshot this attempt restored (or, for a fresh attempt
        resuming a crashed run that never checkpointed, after offset
        zero); ``intent > seq`` on top catches a PARTIALLY applied
        pipeline — the intent record is the first command of every flush
        and the commit seq the last, so a timeout that landed a prefix
        leaves intent ahead.  Either way replayed increments would
        double-count, so the attempt switches to absolute ledger
        reconciliation for every window it flushes.  A failed read means
        the sink cannot be proven clean: reconcile conservatively and
        retry the attach at the next flush."""
        if self._xo_attached or self.redis is None:
            return
        base_e, base_s = self._xo_baseline
        try:
            e, s, i = read_fence(self.redis, self._fence_key)
        except Exception:
            self.faults.inc("fence_read_errors")
            self._reconcile_all = True
            return   # _xo_attached stays False: retry next flush
        if max(s, i) > base_s:
            if not self._reconcile_all:
                self.faults.inc("sink_unfenced_resumes")
            self._reconcile_all = True
        epoch = max(e, base_e) + 1
        try:
            claim_epoch(self.redis, self._fence_key, epoch)
        except Exception:
            # claim failed: retry the WHOLE attach next flush (nothing
            # is ever submitted without a claimed epoch — see
            # _apply_fenced for why a lazy writer-side claim is unsafe).
            # If the claim actually landed (a response-lost timeout),
            # the re-read sees our epoch and simply claims the next one.
            self.faults.inc("fence_read_errors")
            return
        self._sink_epoch = epoch
        self._sink_seq0 = max(s, i, base_s)
        self._xo_attached = True

    def _fence_state(self) -> tuple[int, int]:
        """The (epoch, committed seq) a snapshot records.  Stable only
        after ``drain_writes`` (``_snapshot_sync`` guarantees it)."""
        if self._writer is not None and self._xo:
            return self._writer.fence_state()
        if self._sink_epoch is not None:
            return (self._sink_epoch, self._sink_seq0)
        return self._xo_baseline

    def _flush_exactly_once(self, time_updated: int | None) -> int:
        """The fenced flush path.  Deltas fold into the cumulative
        per-window ledger first; tainted windows (earlier flush failed or
        may have partially applied) and — in reconcile mode — every
        window are written ABSOLUTE from the ledger (idempotent: any
        number of applications lands the same count); the rest go as the
        canonical HINCRBY deltas.  Each submitted batch carries its
        (epoch, seq) fence inside the same pipeline.

        The ledger is the single source of truth for "what the sink
        should hold": it is updated exactly once per delta (reclaimed
        failed batches taint windows instead of re-merging, see
        ``_reclaim_failed_writes``), carried in snapshots, and rebuilt by
        replay after a resume — so an absolute write is always safe, no
        matter what prefix of earlier flushes actually landed."""
        self._xo_attach_sink()
        self._fold_pending_arrays()
        if not self._pending and not self._taint:
            return 0
        if self.redis is not None and self._sink_epoch is None:
            # No claimed epoch (sink unreachable at attach): flushing
            # unfenced would forfeit both the zombie guard and resume
            # detection.  Hold everything — _pending is exactly the
            # retention buffer — and retry the attach next flush.
            return 0
        totals = self._sink_totals
        for key, n in self._pending.items():
            if self.absolute_counts:
                totals[key] = n        # absolute engines: freshest wins
            else:
                totals[key] = totals.get(key, 0) + n
        if self._reconcile_all:
            abs_keys = self._taint | set(self._pending)
            delta_keys: list = []
        else:
            abs_keys = set(self._taint)
            delta_keys = [k for k in self._pending if k not in abs_keys]
        campaigns = self.encoder.campaigns
        rows_abs = [(campaigns[c], ts, totals[(c, ts)])
                    for (c, ts) in sorted(abs_keys)]
        rows_delta = [(campaigns[c], ts, self._pending[(c, ts)])
                      for (c, ts) in delta_keys]
        self._pending.clear()
        self._taint.clear()
        if rows_abs:
            self.faults.inc("reconciled_windows", len(rows_abs))
        if self._obs_lifecycle is not None:
            self._obs_lifecycle.note_flush(
                [ts for _, ts, _ in rows_abs] +
                [ts for _, ts, _ in rows_delta])
        total = len(rows_abs) + len(rows_delta)
        if self.redis is not None:
            writer = self._ensure_writer()
            # Ledger rewrites first: FIFO submission order keeps an
            # absolute reconcile of a window strictly ahead of any later
            # delta to it, so HINCRBY always lands on a reconciled base.
            if rows_abs:
                writer.submit(rows_abs, time_updated, absolute=True)
            if rows_delta:
                writer.submit(rows_delta, time_updated,
                              absolute=self.absolute_counts)
        else:
            stamp = now_ms() if time_updated is None else time_updated
            if rows_abs:
                self._note_written(rows_abs, stamp)
            if rows_delta:
                self._note_written(rows_delta, stamp)
        return total

    def _native_table(self):
        """(names_blob, names_off, native_store) when the sink is the
        in-process native store, else None; built once.  Exactly-once
        mode always returns None: the C array writeback has no fence
        hook, and the fence must ride the SAME pipeline as its rows."""
        if self._xo:
            return None
        if self._camp_table is False:
            tbl = None
            store = getattr(self.redis, "_store", None)
            if store is not None and hasattr(store,
                                             "write_windows_arrays"):
                names = [c.encode() for c in self.encoder.campaigns]
                off = np.zeros(len(names) + 1, np.int64)
                np.cumsum([len(b) for b in names], out=off[1:])
                tbl = (b"".join(names), off, store)
            self._camp_table = tbl
        return self._camp_table

    def _note_written(self, payload, stamp: int) -> None:
        """Latency + write-count bookkeeping at actual write time (writer
        thread) — counting at submit time would double-count rows that
        fail, get reclaimed, and are retried.  When telemetry is
        attached, each unique window's writeback latency also lands in
        the live log-bucketed histogram (O(1) per window, writer-thread
        cadence — never the host loop)."""
        if isinstance(payload, _ArrayRows):
            self.windows_written += len(payload)
            uniq = [int(t) for t in _unique_ts(payload.ts).tolist()]
            for t in uniq:
                self.window_latency[t] = stamp - t
            if self._obs_hist is not None:
                for t in uniq:
                    self._obs_hist.observe(stamp - t)
            if self._obs_lifecycle is not None:
                self._obs_lifecycle.note_written(uniq, stamp)
            self.latency_tracker.record_bulk(
                payload.ci, payload.ts, stamp, payload.campaigns)
            return
        self.windows_written += len(payload)
        for camp, ts, _ in payload:
            self.window_latency[ts] = stamp - ts
            self.latency_tracker.record(camp, ts, stamp)
        if self._obs_hist is not None or self._obs_lifecycle is not None:
            uniq = {ts for _, ts, _ in payload}
            if self._obs_hist is not None:
                for ts in uniq:
                    self._obs_hist.observe(stamp - ts)
            if self._obs_lifecycle is not None:
                self._obs_lifecycle.note_written(uniq, stamp)

    def _reclaim_failed_writes(self) -> None:
        """Fold failed writeback batches back into ``_pending`` so the
        next flush retries them (and snapshots never lose them)."""
        if self._writer is None:
            return
        idx = self.encoder.campaign_index
        for batch in self._writer.take_failed():
            self.faults.inc("sink_retries", len(batch))
            if self._xo:
                # The ledger already counted these deltas when they left
                # for the writer, and a failed pipeline may have landed a
                # PREFIX of them (the partial-apply fault): re-merging
                # would double-count, dropping would under-count.  Taint
                # the windows instead — the next fenced flush rewrites
                # them ABSOLUTE from the ledger, erasing whatever prefix
                # actually landed.
                self._taint.update((idx[camp], int(ts))
                                   for camp, ts, _ in batch)
                continue
            for camp, ts, n in batch:
                if self.absolute_counts:
                    # A fresher re-drained estimate already in _pending
                    # supersedes the stale failed one — never clobber it.
                    self._pending.setdefault((idx[camp], ts), n)
                else:
                    self._pending[(idx[camp], ts)] += n

    # ------------------------------------------------------------------
    # live telemetry (obs/): both hooks are pull-oriented — the sampler
    # thread polls host-side bookkeeping; the only pushed signal is the
    # writeback-latency histogram fed from the writer thread.
    def attach_obs(self, registry, lifecycle: bool = False,
                   spans=None, occupancy=None, xfer=None,
                   shard=None) -> None:
        """Opt into live telemetry: register the window-latency streaming
        histogram on ``registry`` (obs.MetricsRegistry) so p50/p95/p99
        writeback latency is queryable *during* the run — the live
        complement of the exact close-time decile table.  Never called
        on the default path; everything else the sampler needs it pulls
        via ``telemetry()``.

        ``lifecycle=True`` additionally attaches the per-window
        attribution tracker (obs.lifecycle): encode stamps ride the
        batches, the watermark-note hook records folds, and each
        writeback decomposes its latency into
        ingest/encode/fold/flush/sink segment histograms on the same
        registry.

        ``spans`` (obs.spans.SpanTracer) forwards every Tracer stage
        span — encode, device_step/scan, drain, redis_flush (the
        writer thread's sink spans included) — into the bounded
        thread-aware ring for Chrome-trace export.

        ``occupancy`` (obs.occupancy.OccupancySampler) is called after
        every device dispatch; 1-in-N dispatches are timed to
        ``block_until_ready`` completion for the measured
        device-busy ratio.

        ``xfer`` (obs.xfer.TransferLedger) accounts every dispatch's
        host->device payload bytes by wire format, with 1-in-N timed
        transfer samples.

        ``shard`` (obs.xfer.ShardSkew) receives per-shard routed/wanted
        row vectors from the sharded engines' shard-stats kernels (the
        single-device engines accept and ignore it)."""
        self._obs_hist = registry.histogram(
            "streambench_window_latency_ms",
            "window writeback latency (time_updated - window_ts), ms")
        if lifecycle:
            from streambench_tpu.obs.lifecycle import WindowLifecycle

            self._obs_lifecycle = WindowLifecycle(
                registry, divisor_ms=self.divisor,
                lateness_ms=self.lateness)
        if spans is not None:
            spans.attach(self.tracer)
        if occupancy is not None:
            self._obs_occupancy = occupancy
        if xfer is not None:
            self._obs_xfer = xfer
        if shard is not None:
            self._obs_shard = shard

    def telemetry(self) -> dict:
        """Point-in-time observability snapshot of host bookkeeping.
        Plain field reads + one wall-clock call: no device sync, no
        drain, safe from the sampler thread at any cadence."""
        wm = self._host_wm
        writer = self._writer
        out = {
            "events": self.events_processed,
            "windows_written": self.windows_written,
            "watermark_lag_ms": (now_ms() - wm) if wm is not None else None,
            "sink_dirty_rows": (writer.dirty_rows()
                                if writer is not None else 0),
            # parked/pending flush backlog (dict rows + drained triples);
            # tuple() snapshots the list atomically under the GIL so the
            # host loop can append/clear concurrently
            "pending_rows": (len(self._pending)
                             + sum(int(t[0].shape[0])
                                   for t in tuple(self._pending_np))),
        }
        if self._xo:
            e, s = self._fence_state()
            out["sink_fence"] = {"epoch": e, "seq": s,
                                 "reconcile": self._reconcile_all,
                                 "tainted_windows": len(self._taint)}
        if self._devdecode is not None:
            out["device_decode"] = self._devdecode.telemetry()
        return out

    def drain_writes(self) -> None:
        """Block until every queued Redis writeback has landed.  The sync
        point before a checkpoint commits (queued-but-unwritten rows left
        pending at a crash would otherwise be lost: the journal re-tail
        starts past the events that produced them)."""
        if self._writer is not None:
            self._writer.drain()

    # ------------------------------------------------------------------
    # checkpoint/resume (SURVEY.md §5.4 — absent in the reference; the
    # scan carry is fixed-shape arrays, so a snapshot is one savez)
    def _snapshot_sync(self) -> None:
        """Make host bookkeeping snapshot-complete: parked drain deltas
        live in neither state.counts (zeroed) nor _pending — fold them
        in; queued Redis writebacks must land before the snapshot commits
        (see drain_writes); batches whose write FAILED get reclaimed into
        _pending so the snapshot carries them.  Every snapshot() override
        calls this first."""
        self._materialize_drains()
        self._fold_pending_arrays()
        self.drain_writes()
        self._reclaim_failed_writes()

    def _snapshot_meta(self) -> dict:
        """Host-side meta shared by every engine family's snapshot."""
        return dict(
            engine_family=self.ENGINE_FAMILY,
            base_time_ms=self.encoder.base_time_ms,
            divisor_ms=self.divisor,
            lateness_ms=self.lateness,
            window_slots=self.W,
            span_start=self._span_start,
            events_processed=self.events_processed,
            windows_written=self.windows_written,
            started_ms=self.started_ms,
            last_event_ms=self.last_event_ms,
            num_campaigns=self.encoder.num_campaigns,
        )

    def snapshot(self, offset: int) -> "Snapshot":
        """Capture exact engine state as of journal byte ``offset``."""
        from streambench_tpu.checkpoint import Snapshot

        self._snapshot_sync()
        return self._xo_decorate(Snapshot(
            offset=offset,
            meta=self._snapshot_meta(),
            counts=np.asarray(self.state.counts),
            window_ids=np.asarray(self.state.window_ids),
            watermark=int(self.state.watermark),
            dropped=int(self.state.dropped),
            pending=[(c, ts, n) for (c, ts), n in self._pending.items()],
            latency=sorted(self.window_latency.items()),
        ))

    def _xo_decorate(self, snap: "Snapshot") -> "Snapshot":
        """Attach the exactly-once ledger/taint/fence to a snapshot (a
        no-op with the flag off — snapshots stay byte-identical).  Every
        engine family's ``snapshot()`` routes its built Snapshot through
        here so resume-side reconciliation works for all of them.  Call
        AFTER ``_snapshot_sync``: the fence must be the writer's drained,
        committed seq and the taint set must include reclaimed
        failures."""
        if not self._xo:
            return snap
        e, s = self._fence_state()
        snap.meta["sink_epoch"] = int(e)
        snap.meta["sink_seq"] = int(s)
        snap.extra["xo_totals"] = np.asarray(
            [(c, ts, n)
             for (c, ts), n in sorted(self._sink_totals.items())],
            np.int64).reshape(-1, 3)
        snap.extra["xo_taint"] = np.asarray(
            sorted(self._taint), np.int64).reshape(-1, 2)
        return snap

    def _check_geometry(self, snap: "Snapshot",
                        extra: dict[str, int] | None = None) -> None:
        """Family + ring-geometry validation.  Window ids are relative to
        divisor and base, slots to W — reinterpreting any of them silently
        corrupts counts (the span guard would be sized for the wrong
        ring), so a mismatch is a hard error, never a best-effort load."""
        fam = snap.meta.get("engine_family", "exact")
        if fam != self.ENGINE_FAMILY:
            raise ValueError(
                f"checkpoint was written by engine family {fam!r}; this "
                f"engine is {self.ENGINE_FAMILY!r} — device state is not "
                "interchangeable across families")
        checks = dict(num_campaigns=self.encoder.num_campaigns,
                      divisor_ms=self.divisor,
                      lateness_ms=self.lateness,
                      window_slots=self.W)
        checks.update(extra or {})
        for key, mine in checks.items():
            if int(snap.meta[key]) != mine:
                raise ValueError(
                    f"checkpoint {key}={snap.meta[key]} != engine {mine}; "
                    "restart with the original config or discard the "
                    "checkpoint")

    def _restore_host(self, snap: "Snapshot") -> None:
        """Re-establish every host-side field from snapshot meta."""
        self.drain_writes()
        self._undrained.clear()
        self._undrained_ready.clear()
        self._dirty_rows = []
        if self._track_dirty_rows() and snap.counts.size:
            # restored counts may hold undrained cells the tracker never
            # saw — seed it with their rows so the next drain finds them
            # (HERE, not in restore(): every engine family's restore
            # override calls _restore_host, so all of them inherit this)
            live = np.nonzero(np.asarray(snap.counts).any(axis=1))[0]
            if live.size:
                self._dirty_rows.append(live)
        self.encoder.set_base_time(snap.meta["base_time_ms"])
        self._span_start = snap.meta["span_start"]
        # Gate on the NEG "no events" sentinel explicitly: a truthiness
        # check treated a legitimate relative watermark of 0 as unset
        # (span under-measured after restore) and the NEG sentinel as set
        # (host_wm = base - 2e9, span inflated).  A None base means the
        # snapshot predates the first event — nothing to mirror.
        wm = int(snap.watermark)
        base = snap.meta["base_time_ms"]
        self._host_wm = (int(base) + wm
                         if base is not None and wm > wc.NEG else None)
        self.events_processed = int(snap.meta["events_processed"])
        self.windows_written = int(snap.meta["windows_written"])
        self.started_ms = int(snap.meta["started_ms"])
        self.last_event_ms = int(snap.meta["last_event_ms"])
        self._pending = defaultdict(int)
        self._pending_np = []
        for c, ts, n in snap.pending:
            self._pending[(int(c), int(ts))] = int(n)
        self.window_latency = {int(ts): int(v) for ts, v in snap.latency}
        # exactly-once bookkeeping (flag off: the arrays are absent and
        # everything below resets to its dormant state).  The sink fence
        # itself is read lazily at the first flush (_xo_attach_sink) —
        # the comparison baseline restored here is what that read is
        # judged against.
        self._sink_totals = {
            (int(c), int(ts)): int(n)
            for c, ts, n in snap.extra.get(
                "xo_totals", np.empty((0, 3), np.int64))}
        self._taint = {(int(c), int(ts))
                       for c, ts in snap.extra.get(
                           "xo_taint", np.empty((0, 2), np.int64))}
        self._xo_baseline = (int(snap.meta.get("sink_epoch", 0)),
                             int(snap.meta.get("sink_seq", 0)))
        self._reconcile_all = False
        self._xo_attached = not self._xo
        self._sink_epoch = None
        self._sink_seq0 = 0

    def restore(self, snap: "Snapshot") -> None:
        """Reset this engine to a snapshot; caller re-tails the journal at
        ``snap.offset``."""
        self._check_geometry(snap)
        self.state = self._put_state(
            snap.counts, snap.window_ids, snap.watermark, snap.dropped)
        self._restore_host(snap)

    def _put_state(self, counts, window_ids, watermark, dropped):
        """Place restored host arrays on device (subclass hook: the sharded
        engine re-applies its mesh shardings)."""
        return wc.WindowState(
            counts=jnp.asarray(counts), window_ids=jnp.asarray(window_ids),
            watermark=jnp.int32(watermark), dropped=jnp.int32(dropped))

    # ------------------------------------------------------------------
    # Bounded shutdown retry: a transient sink outage at close must not
    # abandon the last flush's rows (the writer's backoff paces attempts;
    # past this many the outage is treated as permanent and close raises).
    CLOSE_RETRY_LIMIT = 8

    def _close_unwritten(self) -> int:
        """Window rows still unflushed at close: writer-retained failed
        batches, plus — exactly-once mode — pending/tainted windows a
        sink-unreachable attach kept from ever being submitted."""
        n = self._writer.dirty_rows() if self._writer is not None else 0
        if self._xo:
            n += len(self._pending) + len(self._taint)
        return n

    def close(self) -> None:
        """Final flush + fork-style latency dump
        (``AdvertisingTopologyNative.java:521-532``).  Retries the final
        writeback up to ``CLOSE_RETRY_LIMIT`` times under the writer's
        backoff before declaring the rows lost."""
        self.flush(final=True)
        if self._writer is not None:
            self._writer.drain()
        for _ in range(self.CLOSE_RETRY_LIMIT):
            if not self._close_unwritten():
                break
            self.flush(final=True)  # reclaims failed rows, resubmits
            if self._writer is not None:
                self._writer.drain()
        if self._writer is None and self._close_unwritten():
            # exactly-once with the sink down since before the first
            # flush: no writer was ever started, so the raise below
            # cannot fire — account and raise here instead (a
            # silent-loss exit is not an option in any mode).
            lost = self._close_unwritten()
            self.faults.inc("rows_lost", lost)
            raise RuntimeError(
                f"exactly-once close with {lost} windows never flushed "
                "(sink unreachable: no writer epoch was ever claimed)")
        if self._encode_pool is not None:
            self._encode_pool.close()
            self._encode_pool = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self.redis is not None and self.cfg.redis_hashtable:
            dump_latency_hash(
                self.redis, self.cfg.redis_hashtable, self.window_latency,
                running_time_ms=self.last_event_ms - self.started_ms)

    @property
    def dropped(self) -> int:
        return int(self.state.dropped)
