"""Incremental delta ships: O(ΔC) dirty-row state shipping (ISSUE 18).

The full-plane ship (:class:`~streambench_tpu.reach.replica.
SnapshotShipper`) gathers and base64-encodes every campaign row on
every cadence tick — O(C) work and bytes even when a tick touched 0.1%
of campaigns, which is exactly the term that makes "millions of
campaigns" incompatible with a tight cadence (the autoscaler's
``ship_cadence`` knob gets MORE expensive exactly when diagnosis says
to turn it).  The sketch planes' merge algebra (elementwise min on the
MinHash signature, max on the HLL registers — commutative, associative,
idempotent; PR 10/13 test-pinned) means a replica that folds only the
changed rows lands bit-identical state, so the wire can carry deltas.

Record chain (all through ``DurableDimensionStore`` — PR 16's ship
fault hook tears/corrupts delta records exactly like bases):

- BASE: the existing ``reach_sketch`` full-plane record, now stamped
  ``seq`` — every base restarts the chain (a reader needs no history
  before it);
- DELTA: a ``reach_delta`` record ``(epoch, seq, ps=prev_seq, idx,
  rows…)`` carrying only the dirty rows of each plane.  A reader folds
  it iff ``ps`` equals the seq it last applied AND the epoch matches;
  any gap, damaged record, or epoch skew breaks the chain and the
  reader serves its last consistent state until the next base resyncs
  it (never a half-folded plane).

The writer (:class:`DeltaShipper`) ships a base on: first ship, any
``force=True`` (close-time AND the restart path — a respawned writer's
dirty set is empty, so forcing a delta would ship nothing and strand
replicas), an epoch bump, every ``base_every``-th record (bounds the
resync window), and whenever ``len(dirty)/C`` crosses
``cutover_frac`` — deltas must never cost more than the thing they
replace.  An empty dirty set still ships a zero-row heartbeat delta at
the cadence so replica staleness stays anchored to live evidence.

Everything is written against a plane-generic surface — a dict of
named arrays plus a per-plane row merge (:data:`REACH_PLANES`) — not
reach-specific fields, so ROADMAP item 2's served-plane generalization
can adopt the shipper verbatim.  Pure numpy; nothing here touches jax.
"""

from __future__ import annotations

import base64
import json
from typing import NamedTuple

import numpy as np

from streambench_tpu.reach.replica import (
    SHIP_KIND,
    SnapshotShipper,
    decode_ship_record,
)

#: the dirty-row record kind (DurableDimensionStore.put_reach_delta)
DELTA_KIND = "reach_delta"

#: ``jax.reach.ship.delta=auto`` floor: below this campaign count the
#: full-plane gather is trivially cheap (a few hundred KB) and the
#: dirty-mask bookkeeping buys nothing
DELTA_AUTO_MIN_CAMPAIGNS = 4096

#: default base cadence: one full record every N ships bounds how far
#: a desynced reader can trail before it resyncs
DEFAULT_BASE_EVERY = 64

#: default Δ/C cutover: above this dirty fraction a delta record stops
#: being meaningfully cheaper than a base (row payload parity is at
#: 1.0; the margin covers the idx column + per-record overhead)
DEFAULT_CUTOVER_FRAC = 0.5


class PlaneSpec(NamedTuple):
    """One named state plane and how its rows merge.

    ``key`` is the planes()-dict / folded-view key, ``wire`` the JSON
    field, ``width_key`` the JSON field naming the row width, ``merge``
    the elementwise row algebra ("min" or "max" — both commutative,
    associative, idempotent, which is what makes delta folds exact)."""

    key: str
    wire: str
    width_key: str
    dtype: type
    merge: str


#: the reach planes: MinHash signature mins (elementwise min) + HLL
#: registers (elementwise max) — matches ops/minhash.merge exactly
REACH_PLANES = (
    PlaneSpec("mins", "mins", "k", np.uint32, "min"),
    PlaneSpec("registers", "regs", "r", np.int32, "max"),
)


def merge_rows(planes: dict, idx: np.ndarray, rows: dict,
               specs=REACH_PLANES) -> None:
    """Fold delta ``rows`` into ``planes`` at ``idx`` via each plane's
    merge algebra, in place (read-only arrays — ``np.frombuffer``
    views — are copied into ``planes`` first)."""
    for sp in specs:
        dst = planes[sp.key]
        if not dst.flags.writeable:
            dst = planes[sp.key] = dst.copy()
        fn = np.minimum if sp.merge == "min" else np.maximum
        dst[idx] = fn(dst[idx], rows[sp.key])


def decode_delta_record(rec: dict, specs=REACH_PLANES) -> dict | None:
    """One parsed delta line -> ``{idx, rows, epoch, seq, ps, …}``, or
    None when torn/corrupt (the chain-break signal)."""
    if rec.get("kind") != DELTA_KIND:
        return None
    try:
        seq, ps = int(rec["seq"]), int(rec["ps"])
        idx = np.frombuffer(base64.b64decode(rec["idx"]), np.int32)
        rows = {}
        for sp in specs:
            w = int(rec[sp.width_key])
            rows[sp.key] = np.frombuffer(
                base64.b64decode(rec[sp.wire]),
                sp.dtype).reshape(len(idx), w)
    except (KeyError, ValueError, TypeError):
        return None
    return {"idx": idx, "rows": rows, "epoch": int(rec.get("epoch", 0)),
            "seq": seq, "ps": ps, "watermark": rec.get("wm"),
            "shipped_ms": int(rec.get("t", 0)),
            "folded_ms": rec.get("fm"), "submit_ms": rec.get("sm"),
            "origin": rec.get("origin")}


class DeltaShipper(SnapshotShipper):
    """Writer-side O(ΔC) shipper: dirty rows ride chain-stamped delta
    records between periodic bases.  Drop-in for
    :class:`SnapshotShipper` (same ``due``/``note_state`` surface) —
    the engine additionally passes its dirty row set and enables
    host-side dirty tracking because ``wants_dirty`` is True."""

    wants_dirty = True
    mode = "delta"

    def __init__(self, store, campaigns: list[str],
                 interval_ms: int = 1000, registry=None,
                 origin: dict | None = None, specs=REACH_PLANES,
                 base_every: int = DEFAULT_BASE_EVERY,
                 cutover_frac: float = DEFAULT_CUTOVER_FRAC):
        super().__init__(store, campaigns, interval_ms=interval_ms,
                         registry=registry, origin=origin)
        self.specs = tuple(specs)
        self.base_every = max(int(base_every), 1)
        self.cutover_frac = float(cutover_frac)
        self.bases = 0
        self.deltas = 0
        self.cutovers = 0
        self._seq = 0              # last shipped record's chain stamp
        self._since_base = 0

    def note_state(self, mins, registers, epoch: int,
                   watermark: int = 0, force: bool = False,
                   folded_ms: int | None = None,
                   dirty_rows=None) -> bool:
        return self.note_planes(
            {"mins": mins, "registers": registers}, epoch,
            watermark=watermark, force=force, folded_ms=folded_ms,
            dirty_rows=dirty_rows)

    def note_planes(self, planes: dict, epoch: int, *,
                    watermark: int = 0, force: bool = False,
                    folded_ms: int | None = None,
                    dirty_rows=None) -> bool:
        """Plane-generic ship: ``planes`` is a dict of named arrays
        matching ``self.specs``; ``dirty_rows`` the row indices touched
        since the last ship (None = unknown -> base).  Returns True
        when a record was written."""
        import time as _time

        from streambench_tpu.utils.ids import now_ms

        now = _time.monotonic()
        epoch = int(epoch)
        if (not force and self._last_epoch == epoch
                and (now - self._last_ship) * 1000.0 < self.interval_ms):
            return False
        t0 = _time.perf_counter()
        np_planes = {sp.key: np.asarray(planes[sp.key])
                     for sp in self.specs}
        C = int(np_planes[self.specs[0].key].shape[0])
        if dirty_rows is None:
            dirty = None
        else:
            dirty = np.ascontiguousarray(
                np.asarray(dirty_rows).ravel(), dtype=np.int32)
        cutover = (dirty is not None
                   and dirty.size >= self.cutover_frac * C)
        # force covers the restart path (ISSUE 18 satellite bugfix): a
        # respawned writer's dirty set is EMPTY — a forced delta would
        # ship nothing and strand replicas until the next organic base
        need_base = (force or dirty is None
                     or self._last_epoch != epoch
                     or self._since_base >= self.base_every
                     or cutover)
        submit_ms = now_ms()
        seq = self._seq + 1
        if need_base:
            if cutover and not force and self._last_epoch == epoch:
                self.cutovers += 1
            nbytes = self.store.put_reach_sketches(
                np_planes["mins"], np_planes["registers"],
                self.campaigns, epoch, watermark=int(watermark),
                folded_ms=(int(folded_ms) if folded_ms is not None
                           else submit_ms),
                submit_ms=submit_ms, origin=self.origin, seq=seq)
            rows_n = C
            self.bases += 1
            self._since_base = 0
        else:
            rows = {sp.wire: np.ascontiguousarray(
                        np_planes[sp.key][dirty], dtype=sp.dtype)
                    for sp in self.specs}
            nbytes = self.store.put_reach_delta(
                dirty, rows, epoch=epoch, seq=seq, prev_seq=self._seq,
                watermark=int(watermark),
                folded_ms=(int(folded_ms) if folded_ms is not None
                           else submit_ms),
                submit_ms=submit_ms, origin=self.origin)
            rows_n = int(dirty.size)
            self.deltas += 1
            self._since_base += 1
        self._seq = seq
        self._mark_shipped(now, epoch, nbytes, rows_n,
                           (_time.perf_counter() - t0) * 1e3)
        return True

    def summary(self) -> dict:
        out = super().summary()
        out.update(bases=self.bases, deltas=self.deltas,
                   cutovers=self.cutovers, base_every=self.base_every,
                   cutover_frac=self.cutover_frac, seq=self._seq)
        return out


class ChainTailer:
    """Chain-validating ship-log consumer: the delta-aware replacement
    for :class:`~streambench_tpu.reach.replica.ShipLogTailer`.

    Each ``poll`` consumes newly appended complete lines in order
    (torn tails stay buffered until the newline lands), loads bases,
    folds chain-consistent deltas via :func:`merge_rows`, and returns
    the folded view — the same dict shape ``decode_ship_record``
    produces — when anything was applied, else None.  Any gap (missing
    ``ps`` link, damaged record, epoch skew) breaks the chain: deltas
    are discarded and the view stays at the last consistent state (it
    ages until the replica's staleness bound sheds) until the next
    base resyncs.  Over a base-only log (full-ship mode) this behaves
    exactly like the legacy tailer: the newest base wins.

    The returned plane arrays are owned by the tailer and mutated
    across polls — consumers that retain them (rather than converting
    to device arrays immediately) must copy."""

    def __init__(self, path: str, specs=REACH_PLANES):
        self.path = path
        self.specs = tuple(specs)
        self._pos = 0
        self._carry = b""
        self._view: dict | None = None
        self._seq: int | None = None    # None = chain cannot extend
        self.records_seen = 0
        self.bases_loaded = 0
        self.deltas_folded = 0
        self.gaps = 0
        self.damaged = 0
        self.resyncs = 0

    def _apply_base(self, rec: dict) -> bool:
        view = decode_ship_record(rec)
        if view is None:
            self.damaged += 1
            return False
        if self._view is not None and self._seq is None:
            self.resyncs += 1
        self._view = view
        # a legacy (pre-chain) base has no seq: it loads fine but no
        # delta can chain off it — exactly right, legacy writers never
        # emit deltas
        self._seq = rec.get("seq")
        self.bases_loaded += 1
        return True

    def _apply_delta(self, rec: dict) -> bool:
        if self._view is None or self._seq is None:
            self.gaps += 1
            return False
        d = decode_delta_record(rec, self.specs)
        if d is None:
            # a damaged delta is a lost link even when the NEXT record
            # would chain: break now, resync at the next base
            self.damaged += 1
            self._seq = None
            return False
        C = len(self._view["campaigns"])
        if (d["epoch"] != self._view["epoch"] or d["ps"] != self._seq
                or (d["idx"].size and (int(d["idx"].min()) < 0
                                       or int(d["idx"].max()) >= C))):
            self.gaps += 1
            self._seq = None
            return False
        merge_rows(self._view, d["idx"], d["rows"], self.specs)
        if d["watermark"] is not None:
            self._view["watermark"] = int(d["watermark"])
        self._view["shipped_ms"] = d["shipped_ms"]
        self._view["folded_ms"] = d["folded_ms"]
        self._view["submit_ms"] = d["submit_ms"]
        if d["origin"] is not None:
            self._view["origin"] = d["origin"]
        self._seq = d["seq"]
        self.deltas_folded += 1
        return True

    def poll(self) -> dict | None:
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                data = f.read()
        except FileNotFoundError:
            return None
        if not data:
            return None
        self._pos += len(data)
        data = self._carry + data
        nl = data.rfind(b"\n") + 1
        self._carry = data[nl:]
        changed = False
        for line in data[:nl].splitlines():
            line = line.strip()
            if not line:
                continue
            is_base = b'"reach_sketch"' in line
            is_delta = b'"reach_delta"' in line
            if not (is_base or is_delta):
                continue
            self.records_seen += 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # an unparseable ship line may have been a chain link;
                # the seq stamps catch the loss at the next delta, so
                # only count the damage here
                self.damaged += 1
                continue
            if rec.get("kind") == SHIP_KIND:
                changed = self._apply_base(rec) or changed
            elif rec.get("kind") == DELTA_KIND:
                changed = self._apply_delta(rec) or changed
        return dict(self._view) if changed else None

    def stats(self) -> dict:
        return {"records_seen": self.records_seen,
                "bases_loaded": self.bases_loaded,
                "deltas_folded": self.deltas_folded,
                "gaps": self.gaps, "damaged": self.damaged,
                "resyncs": self.resyncs,
                "seq": self._seq,
                "epoch": (self._view or {}).get("epoch")}
