"""Freshness-aware failover router for the reach replica fleet
(ISSUE 16 headline; ROADMAP item 3(b)).

One fronting process owns the client-facing ``reach`` verb and fans
query batches out across N replicas:

- **sticky routing**: the primary replica for a query is chosen by a
  STABLE hash of its campaign set (crc32 over the sorted names — not
  Python's salted ``hash``), so repeats of the same set land on the
  same replica and its (epoch, campaign-set) result cache keeps
  hitting;
- **freshness-ordered failover**: every reply already carries
  ``staleness_ms`` + the per-hop freshness ledger (PR 15) and every
  shed carries its reason — the router folds both into a per-replica
  health ledger (last staleness, epoch, timeouts, shed counts,
  consecutive failures) and, when the primary times out / errors /
  sheds, retries the NEXT-FRESHEST replica rather than a random one;
- **honest shed**: when every replica is outside the staleness bound
  (or down), the router answers ``{"shed": true, "reason":
  "all_stale" | "overloaded" | "no_replica"}`` — it never silently
  serves stale-beyond-bound evidence and never drops a query on the
  floor.  ``sent == answered + shed`` is the accounting invariant
  ``chaos.verify.check_fleet_accounting`` asserts over request ids.

Forwarded requests use router-internal ids (the pub/sub request-id
dedup and the timeout/retry path key on them); the client's own id is
restored on the reply, so a routed answer is byte-identical to a
direct replica answer — the router adds NO fields to a served reply.

Run one per fleet::

    python -m streambench_tpu.reach.router \
        --replicas 127.0.0.1:7001,127.0.0.1:7002 --port 0

The process prints ``router: pubsub=<host>:<port> replicas=<n>`` once
serving (harness/CI parse it) and one JSON stats line at exit.
"""

from __future__ import annotations

import json
import threading
import time
import zlib

from streambench_tpu.utils.ids import now_ms

#: per-attempt reply deadline + bounded same-replica retries (each
#: retry uses a fresh derived id; the replica answers each id once)
DEFAULT_TIMEOUT_S = 2.0
DEFAULT_RETRIES = 1

#: a replica with this many consecutive failures is demoted to the
#: END of the failover order until the cooldown passes — the sticky
#: primary must not tax every query with a dead replica's timeout
SUSPECT_AFTER = 2
SUSPECT_COOLDOWN_S = 5.0

#: summary() reports answered-query e2e percentiles over this many
#: trailing seconds (the autoscaler's latency evidence must decay
#: after a burst, or a past breach would read as a live one forever)
E2E_WINDOW_S = 5.0


def campaign_shard(campaigns, n: int) -> int:
    """Stable shard index for a campaign set: crc32 over the sorted,
    comma-joined names.  Deterministic across processes and runs
    (Python's ``hash`` is salted per process), insensitive to query
    order — ``{a,b}`` and ``{b,a}`` are the same cache line."""
    key = ",".join(sorted(str(c) for c in campaigns))
    return zlib.crc32(key.encode()) % max(int(n), 1)


class ReplicaHandle:
    """Router-side view of one replica endpoint: a persistent
    JSON-lines client plus the health ledger failover ordering reads.
    Thread-safe: one lock serializes the connection, the ledger fields
    are GIL-atomic scalar writes."""

    def __init__(self, addr: str, *, timeout_s: float = DEFAULT_TIMEOUT_S,
                 retries: int = DEFAULT_RETRIES):
        self.addr = str(addr)
        host, _, port = self.addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.retries = max(int(retries), 0)
        self._client = None
        self._lock = threading.Lock()
        # health ledger
        self.served = 0
        self.sheds = 0
        self.stale_sheds = 0
        self.timeouts = 0
        self.errors = 0
        self.consecutive_failures = 0
        self.last_staleness_ms: float | None = None
        self.last_epoch: int | None = None
        self._last_failure_mono: float | None = None

    # -- transport -----------------------------------------------------
    def ask(self, msg: dict) -> dict:
        """One id-matched synchronous request.  Raises TimeoutError /
        ConnectionError / OSError; the connection is torn down on any
        failure and rebuilt lazily on the next ask."""
        with self._lock:
            if self._client is None:
                from streambench_tpu.dimensions.pubsub import PubSubClient

                self._client = PubSubClient(self.host, self.port,
                                            timeout_s=self.timeout_s)
            try:
                return self._client.request(msg,
                                            timeout_s=self.timeout_s,
                                            retries=self.retries)
            except (TimeoutError, ConnectionError, OSError):
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = None
                raise

    # -- ledger --------------------------------------------------------
    def note_served(self, data: dict) -> None:
        self.served += 1
        self.consecutive_failures = 0
        stale = data.get("staleness_ms")
        if isinstance(stale, (int, float)):
            self.last_staleness_ms = float(stale)
        epoch = data.get("plane_epoch")
        if isinstance(epoch, int):
            self.last_epoch = epoch

    def note_shed(self, data: dict) -> None:
        self.sheds += 1
        if data.get("reason") == "stale":
            self.stale_sheds += 1
            stale = data.get("staleness_ms")
            if isinstance(stale, (int, float)):
                self.last_staleness_ms = float(stale)
        epoch = data.get("plane_epoch")
        if isinstance(epoch, int):
            self.last_epoch = epoch

    def note_failure(self, timeout: bool) -> None:
        if timeout:
            self.timeouts += 1
        else:
            self.errors += 1
        self.consecutive_failures += 1
        self._last_failure_mono = time.monotonic()

    def suspect(self) -> bool:
        """True while this replica should be tried LAST: enough
        consecutive failures, within the cooldown."""
        if self.consecutive_failures < SUSPECT_AFTER:
            return False
        last = self._last_failure_mono
        return (last is not None
                and time.monotonic() - last < SUSPECT_COOLDOWN_S)

    def freshness_key(self) -> float:
        """Failover sort key: last known staleness, unknowns last
        among the non-suspect (an endpoint that never answered carries
        no freshness evidence)."""
        s = self.last_staleness_ms
        return float(s) if s is not None else float("inf")

    def health(self) -> dict:
        out = {"addr": self.addr, "served": self.served,
               "sheds": self.sheds, "timeouts": self.timeouts,
               "errors": self.errors,
               "suspect": self.suspect()}
        if self.stale_sheds:
            out["stale_sheds"] = self.stale_sheds
        if self.last_staleness_ms is not None:
            out["staleness_ms"] = round(self.last_staleness_ms, 1)
        if self.last_epoch is not None:
            out["plane_epoch"] = self.last_epoch
        return out

    def close(self) -> None:
        with self._lock:
            if self._client is not None:
                try:
                    self._client.close()
                except Exception:
                    pass
                self._client = None


class ReachRouter:
    """The fronting ``reach`` verb over a replica fleet."""

    #: client errors forwarded verbatim instead of failed over — the
    #: next replica would refuse the same malformed query identically
    CLIENT_ERRORS = ("bad_request", "unknown_campaign")

    def __init__(self, replicas, *, host: str = "127.0.0.1",
                 port: int = 0, timeout_s: float = DEFAULT_TIMEOUT_S,
                 retries: int = DEFAULT_RETRIES, registry=None,
                 flightrec=None):
        from streambench_tpu.dimensions.pubsub import PubSubServer

        if not replicas:
            raise ValueError("router needs at least one replica")
        self.timeout_s = float(timeout_s)
        self.retries = retries
        self.handles = [ReplicaHandle(a, timeout_s=timeout_s,
                                      retries=retries)
                        for a in replicas]
        self._flightrec = flightrec
        self.routed = 0
        self.answered = 0
        self.shed = 0
        self.failovers = 0
        self._fail_ring: list = []          # failover episode ms
        self._fail_ring_max = 8192
        # answered-query e2e latency, (monotonic, ms): the fleet's
        # front-door latency — a single serialized replica handle shows
        # up HERE, not in any replica's own submit->reply percentiles.
        # Stamped so summary() reports a recent window, not all-time:
        # the autoscaler must see a burst's pressure decay, not carry
        # it forever (ISSUE 17)
        self._e2e_ring: list = []
        self._e2e_ring_max = 8192
        self._id_lock = threading.Lock()
        self._next = 0
        self._routed_t0: float | None = None
        self._routed_t1: float | None = None
        self._c_failover = self._c_shed = self._g_healthy = None
        if registry is not None:
            self._c_failover = registry.counter(
                "streambench_router_failover_total",
                "queries answered by a non-primary replica after the "
                "primary timed out, errored, or shed")
            self._c_shed = registry.counter(
                "streambench_router_shed_total",
                "queries the router shed because no replica was "
                "inside the staleness bound (or reachable)")
            self._g_healthy = registry.gauge(
                "streambench_router_healthy_replicas",
                "replicas not currently suspect (failover cooldown)")
        self.pubsub = PubSubServer(host=host, port=port)
        self.pubsub.register_query("reach", self._handle)

    # -- routing -------------------------------------------------------
    @property
    def address(self) -> tuple:
        return self.pubsub.address

    def start(self) -> "ReachRouter":
        self.pubsub.start()
        return self

    def _order(self, campaigns) -> list:
        """Sticky primary first, then the rest by freshness; suspects
        (primary included) demoted to the end, still freshness-
        ordered — a down fleet is retried in best-evidence order.
        Snapshots ``self.handles`` once: add/remove_replica swap the
        list atomically, so an in-flight query keeps a consistent
        view."""
        handles = self.handles
        primary = handles[campaign_shard(campaigns, len(handles))]
        rest = sorted((h for h in handles if h is not primary),
                      key=ReplicaHandle.freshness_key)
        order = [primary] + rest
        live = [h for h in order if not h.suspect()]
        dead = [h for h in order if h.suspect()]
        return live + dead

    # -- elastic surface (ISSUE 17): the autoscaler's registry ---------
    def add_replica(self, addr: str) -> ReplicaHandle:
        """Register one more replica endpoint (scale-up).  The sticky
        shard map re-spreads over the new count on the next query; the
        copy-and-swap keeps in-flight `_order` snapshots consistent."""
        h = ReplicaHandle(addr, timeout_s=self.timeout_s,
                          retries=self.retries)
        self.handles = self.handles + [h]
        return h

    def remove_replica(self, addr: str) -> bool:
        """Deregister an endpoint (graceful retire): new queries stop
        routing to it immediately; its connection is closed.  Refuses
        to empty the fleet (the router's constructor invariant);
        returns False for an unknown address."""
        handles = self.handles
        keep = [h for h in handles if h.addr != str(addr)]
        if len(keep) == len(handles):
            return False
        if not keep:
            raise ValueError("router needs at least one replica")
        self.handles = keep
        for h in handles:
            if h.addr == str(addr):
                h.close()
        return True

    def _route_id(self) -> str:
        with self._id_lock:
            self._next += 1
            return f"rt{self._next}"

    def _handle(self, msg: dict, reply) -> None:
        """The pub/sub verb hook: route one query, never raise."""
        t0 = time.monotonic()
        self.routed += 1
        if self._routed_t0 is None:
            self._routed_t0 = t0
        client_id = msg.get("id")
        campaigns = msg.get("campaigns")
        order = self._order(campaigns if isinstance(
            campaigns, (list, tuple)) else ())
        attempts = 0
        saw_stale = saw_shed = False
        for h in order:
            attempts += 1
            fwd = dict(msg)
            fwd["id"] = self._route_id()
            try:
                data = h.ask(fwd)
            except (TimeoutError, ConnectionError, OSError) as e:
                h.note_failure(isinstance(e, TimeoutError))
                self._note_failover_step(h, "error", repr(e))
                continue
            if not isinstance(data, dict):
                h.note_failure(False)
                continue
            if data.get("error") in self.CLIENT_ERRORS:
                # the query itself is malformed: every replica would
                # refuse it identically — forward the refusal, done
                self._finish(reply, data, client_id, t0, attempts)
                return
            if data.get("error"):
                h.note_failure(False)
                self._note_failover_step(h, "error", str(data["error"]))
                continue
            if data.get("shed"):
                h.note_shed(data)
                saw_shed = True
                saw_stale = saw_stale or data.get("reason") == "stale"
                self._note_failover_step(
                    h, "shed", str(data.get("reason") or "depth"))
                continue
            h.note_served(data)
            self._finish(reply, data, client_id, t0, attempts)
            return
        # every replica exhausted: the honest shed
        reason = ("all_stale" if saw_stale
                  else "overloaded" if saw_shed else "no_replica")
        self.shed += 1
        if self._c_shed is not None:
            self._c_shed.inc()
        if self._flightrec is not None:
            self._flightrec.record(
                "router_shed", reason=reason, attempts=attempts,
                shed_total=self.shed, routed=self.routed)
        self._safe_reply(reply, {"shed": True, "reason": reason,
                                 "id": client_id})
        self._routed_t1 = time.monotonic()

    def _finish(self, reply, data: dict, client_id, t0: float,
                attempts: int) -> None:
        out = dict(data)
        out["id"] = client_id
        self._safe_reply(reply, out)
        self.answered += 1
        self._routed_t1 = time.monotonic()
        self._e2e_ring.append(
            (self._routed_t1, (self._routed_t1 - t0) * 1000.0))
        if len(self._e2e_ring) > self._e2e_ring_max:
            del self._e2e_ring[0]
        if attempts > 1:
            self.failovers += 1
            if self._c_failover is not None:
                self._c_failover.inc()
            ms = (self._routed_t1 - t0) * 1000.0
            self._fail_ring.append(ms)
            if len(self._fail_ring) > self._fail_ring_max:
                del self._fail_ring[0]
        if self._g_healthy is not None:
            self._g_healthy.set(
                sum(1 for h in self.handles if not h.suspect()))

    def _note_failover_step(self, h: ReplicaHandle, kind: str,
                            detail: str) -> None:
        if self._flightrec is not None:
            self._flightrec.record(
                "router_failover", replica=h.addr, kind=kind,
                detail=detail[:120], failovers=self.failovers)

    @staticmethod
    def _safe_reply(reply, data: dict) -> None:
        try:
            reply(data)
        except Exception:
            pass   # a dead client must not kill routing

    # -- reporting -----------------------------------------------------
    def summary(self) -> dict:
        out = {
            "routed": self.routed,
            "answered": self.answered,
            "shed": self.shed,
            "failovers": self.failovers,
            "shed_ratio": (round(self.shed / self.routed, 4)
                           if self.routed else 0.0),
            "replicas": [h.health() for h in self.handles],
        }
        if self._fail_ring:
            lats = sorted(self._fail_ring)
            out["failover_p50_ms"] = round(lats[len(lats) // 2], 2)
            out["failover_p99_ms"] = round(
                lats[min(len(lats) - 1, int(len(lats) * 0.99))], 2)
        cutoff = time.monotonic() - E2E_WINDOW_S
        recent = sorted(ms for t, ms in list(self._e2e_ring)
                        if t >= cutoff)
        if recent:
            out["e2e_recent_n"] = len(recent)
            out["e2e_p50_ms"] = round(recent[len(recent) // 2], 2)
            out["e2e_p99_ms"] = round(
                recent[min(len(recent) - 1,
                           int(len(recent) * 0.99))], 2)
        if (self._routed_t0 is not None and self._routed_t1 is not None
                and self._routed_t1 > self._routed_t0 and self.routed):
            out["qps"] = round(
                self.routed / (self._routed_t1 - self._routed_t0), 1)
        return out

    def close(self) -> None:
        self.pubsub.close()
        for h in self.handles:
            h.close()


def main(argv: list[str] | None = None) -> int:
    import argparse
    import os
    import signal

    ap = argparse.ArgumentParser(
        prog="streambench-reach-router", description=__doc__)
    ap.add_argument("--replicas", required=True,
                    help="comma-separated replica pub/sub endpoints "
                         "(host:port,host:port,...)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--timeout-s", type=float, default=DEFAULT_TIMEOUT_S)
    ap.add_argument("--retries", type=int, default=DEFAULT_RETRIES)
    ap.add_argument("--duration", type=float, default=None,
                    help="seconds to serve (default: until SIGTERM)")
    ap.add_argument("--pid-file", default=None,
                    help="write '<pid> <starttime>' here (refuses to "
                         "start when the file names a live process)")
    ap.add_argument("--metrics-dir", default=None,
                    help="workdir for this router's metrics.jsonl + "
                         "flight dumps (FleetCollector reads them like "
                         "any other role)")
    ap.add_argument("--metrics-interval-ms", type=int, default=1000)
    args = ap.parse_args(argv)

    pidfile = None
    if args.pid_file:
        from streambench_tpu.utils.pidfile import acquire_pidfile

        pidfile = acquire_pidfile(args.pid_file)
        if pidfile is None:
            print(f"router: refusing to start, {args.pid_file} names "
                  f"a live process", flush=True)
            return 1

    sampler = flightrec = None
    registry = None
    if args.metrics_dir:
        from streambench_tpu.obs import (
            FlightRecorder,
            MetricsRegistry,
            MetricsSampler,
        )

        os.makedirs(args.metrics_dir, exist_ok=True)
        registry = MetricsRegistry()
        sampler = MetricsSampler(
            os.path.join(args.metrics_dir, "metrics.jsonl"),
            interval_ms=args.metrics_interval_ms, registry=registry,
            role="router")
        flightrec = FlightRecorder(args.metrics_dir)

    replicas = [a.strip() for a in args.replicas.split(",") if a.strip()]
    router = ReachRouter(replicas, host=args.host, port=args.port,
                         timeout_s=args.timeout_s, retries=args.retries,
                         registry=registry, flightrec=flightrec).start()
    if sampler is not None:
        def _collect(rec, dt_s):
            rec["router"] = router.summary()

        sampler.add_collector(_collect)
        sampler.start()
    host, port = router.address
    print(f"router: pubsub={host}:{port} replicas={len(replicas)} "
          f"timeout_s={args.timeout_s}", flush=True)

    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    t0 = now_ms()
    done.wait(args.duration)
    stats = router.summary()
    stats["wall_s"] = round((now_ms() - t0) / 1000.0, 2)
    router.close()
    if flightrec is not None and len(flightrec):
        flightrec.dump("router_exit")
    if sampler is not None:
        sampler.close(final=stats)
    if pidfile is not None:
        from streambench_tpu.utils.pidfile import release_pidfile

        release_pidfile(args.pid_file)
    print(json.dumps(stats), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
