"""Live reach-query serving: MinHash ∪ HLL audience-overlap engine.

Layers (ISSUE 10 / ROADMAP item 4):

- ``ops/minhash.py`` — the cumulative per-campaign sketch state
  (signature + paired HLL plane) folded inside the jitted step;
- ``reach.query`` — one jitted ``batch_query`` that evaluates a *batch*
  of union/intersection/overlap queries in a single dispatch (campaign
  sets as a ``[Q, C]`` membership mask);
- ``reach.serve`` — the bounded, load-shedding query server behind the
  ``dimensions.pubsub`` "reach" verb, with per-query latency histograms
  feeding the ``jax.reach.slo.p99.ms`` objective (obs/slo.py);
- ``reach.oracle`` — exact set-arithmetic ground truth + a numpy mirror
  of the sketch algebra for bit-exact verification (bench_reach.py,
  tests/test_reach_query.py).
"""

from streambench_tpu.reach.query import (
    batch_query,
    overlap_bound,
    query_chunks,
    union_bound,
)
from streambench_tpu.reach.serve import ReachQueryServer

__all__ = [
    "ReachQueryServer",
    "batch_query",
    "overlap_bound",
    "query_chunks",
    "union_bound",
]
