"""Ground truth for reach queries: exact set arithmetic + a numpy
mirror of the sketch algebra.

Two verification strengths (bench_reach.py uses both):

- **bit-exact** at small cardinality: per-campaign device-id sets are
  built by exact set arithmetic over the generator's journal, the
  expected ``[C, k]`` / ``[C, R]`` sketch planes are computed in numpy
  from those *sets* (dedup-invariance of the streamed fold is part of
  what this pins), and query evaluation is mirrored slot-for-slot —
  the device state and the integer collision counts must match
  exactly;
- **statistical** at large cardinality: estimates are compared against
  the exact union/intersection counts and the measured relative error
  must sit inside the theoretical bounds (``reach.query.union_bound``
  / ``overlap_bound``).

The numpy splitmix32/rank mirrors must stay bit-identical to
``ops/hll.py`` / ``ops/minhash.py`` — tests/test_minhash.py pins the
differential.
"""

from __future__ import annotations

import json
import zlib

import numpy as np

from streambench_tpu.ops.minhash import EMPTY, _SALT_GAMMA


def splitmix32_np(x: np.ndarray) -> np.ndarray:
    """numpy mirror of ``ops.hll.splitmix32`` (uint32, wrapping)."""
    x = np.asarray(x).astype(np.uint32)
    x = (x + np.uint32(0x9E3779B9)).astype(np.uint32)
    x = ((x ^ (x >> np.uint32(16)))
         * np.uint32(0x21F0AAAD)).astype(np.uint32)
    x = ((x ^ (x >> np.uint32(15)))
         * np.uint32(0x735A2D97)).astype(np.uint32)
    return (x ^ (x >> np.uint32(15))).astype(np.uint32)


def rank_np(h: np.ndarray, p: int) -> np.ndarray:
    """numpy mirror of ``ops.hll._rank``: 1 + leading-zero count of the
    top (32-p) bits."""
    bits = 32 - p
    w = (h >> np.uint32(p)).astype(np.int64)
    bitlen = np.where(w > 0, np.frexp(w.astype(np.float64))[1], 0)
    return (bits - bitlen + 1).astype(np.int32)


def salts_np(k: int) -> np.ndarray:
    """numpy mirror of ``ops.minhash.salts``."""
    return splitmix32_np(
        (np.arange(1, k + 1, dtype=np.uint32)
         * np.uint32(_SALT_GAMMA)).astype(np.uint32))


def id_hash32(user_id: str | bytes) -> int:
    """The encoder's stateless crc32 id (signed int32 bit pattern) —
    what ``HASHED_IDS`` engines see in the ``user_idx`` column."""
    b = user_id.encode() if isinstance(user_id, str) else user_id
    c = zlib.crc32(b)
    return c - (1 << 32) if c & 0x80000000 else c


def campaign_user_sets(lines, mapping: dict[str, str],
                       campaigns: list[str]) -> dict[str, set[int]]:
    """Exact per-campaign device sets from journal lines: the crc32 ids
    of users with a *view* event joining to each campaign (the same
    filter/join the device fold applies)."""
    sets: dict[str, set[int]] = {c: set() for c in campaigns}
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode()
        line = line.strip()
        if not line:
            continue
        ev = json.loads(line)
        if ev.get("event_type") != "view":
            continue
        campaign = mapping.get(ev.get("ad_id"))
        if campaign is None:
            continue
        sets[campaign].add(id_hash32(ev["user_id"]))
    return sets


def expected_state(sets: dict[str, set[int]], campaigns: list[str],
                   k: int, num_registers: int
                   ) -> tuple[np.ndarray, np.ndarray]:
    """The sketch planes a correct fold must produce, computed from the
    exact sets (order- and duplicate-free by construction)."""
    R = num_registers
    p = R.bit_length() - 1
    mins = np.full((len(campaigns), k), EMPTY, np.uint32)
    regs = np.zeros((len(campaigns), R), np.int32)
    salt = salts_np(k)
    for ci, name in enumerate(campaigns):
        ids = sets.get(name, set())
        if not ids:
            continue
        h = splitmix32_np(np.asarray(sorted(ids), np.int64)
                          .astype(np.uint32))
        hk = splitmix32_np(h[:, None] ^ salt[None, :])
        mins[ci] = hk.min(axis=0)
        j = (h & np.uint32(R - 1)).astype(np.int64)
        rank = rank_np(h, p)
        np.maximum.at(regs[ci], j, rank)
    return mins, regs


def query_oracle_np(mins: np.ndarray, registers: np.ndarray,
                    mask: np.ndarray) -> np.ndarray:
    """numpy mirror of the device query's integer collision count
    (``agree``) per query row — the bit-exact comparison target."""
    sel = mask[:, :, None]
    sel_min = np.where(sel, mins[None], np.uint32(EMPTY)).min(axis=1)
    sel_max = np.where(sel, mins[None], np.uint32(0)).max(axis=1)
    return np.sum((sel_min == sel_max) & (sel_min != np.uint32(EMPTY)),
                  axis=1).astype(np.int32)


def exact_counts(sets: dict[str, set[int]], names: list[str],
                 op: str) -> tuple[int, int]:
    """Exact ``(result, union)`` cardinalities by set arithmetic:
    ``op='union'`` -> (|∪|, |∪|); ``op='overlap'`` -> (|∩|, |∪|)."""
    if not names:
        return 0, 0
    sel = [sets.get(n, set()) for n in names]
    union = set().union(*sel)
    if op == "union":
        return len(union), len(union)
    inter = set(sel[0])
    for s in sel[1:]:
        inter &= s
    return len(inter), len(union)
