"""Batched reach-query evaluation: thousands of ad-hoc queries in a
handful of device dispatches.

A query is ``(campaign set, op)`` with op ∈ {union, overlap}.  A batch
of Q queries is encoded as a ``[Q, C]`` boolean membership mask plus a
``[Q]`` overlap flag and evaluated by ONE jitted program:

- union signature/registers = masked elementwise min/max over the
  selected campaigns (the sketch merges are embarrassingly parallel —
  a [Q, C, k] broadcast + reduction, no per-query host work);
- ``|∪|`` from the merged HLL plane (``hll.estimate``);
- m-way Jaccard from the collision fraction: slot j agrees when every
  selected campaign's minimum equals the union minimum — that happens
  exactly when slot j's argmin device belongs to every selected set,
  so ``P(agree) = |∩|/|∪|`` and ``J_est = agree_count / k``;
- ``|∩| ≈ |∪| · J``.

``query_chunks`` pads query batches to ONE static batch shape so the
whole storm compiles once and dispatches ``ceil(Q/batch)`` times — the
bench asserts that dispatch count, not one dispatch per query.

Error model (the bounds the serving layer returns next to every
estimate): the union estimate carries HLL's relative standard error
``1.04/sqrt(R)``; the overlap estimate's error *as a fraction of the
union* is the Jaccard estimator's ``sqrt(J(1-J)/k) <= 0.5/sqrt(k)``
plus the union term — ``1/sqrt(k)`` (~6.25% at k=256) is the
conservative 2-sigma figure bench_reach.py asserts against exact set
arithmetic.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from streambench_tpu.ops import hll
from streambench_tpu.ops.minhash import EMPTY

#: default queries evaluated per dispatch (padded static shape)
DEFAULT_BATCH = 256


def union_bound(num_registers: int) -> float:
    """Relative standard error of the HLL union estimate."""
    return 1.04 / math.sqrt(num_registers)


def overlap_bound(k: int, num_registers: int) -> float:
    """Conservative relative-to-union error bound for the overlap
    estimate: 2-sigma Jaccard (``1/sqrt(k)``) + the union term."""
    return 1.0 / math.sqrt(k) + union_bound(num_registers)


@jax.jit
def batch_query(mins: jax.Array, registers: jax.Array,
                mask: jax.Array, overlap: jax.Array):
    """Evaluate one padded query batch.

    ``mins [C, k] uint32``, ``registers [C, R] int32``,
    ``mask [Q, C] bool``, ``overlap [Q] bool``.  Returns
    ``(estimate [Q] f32, union [Q] f32, jaccard [Q] f32,
    agree [Q] i32)`` — ``agree`` is the integer collision count, the
    bit-exact quantity the oracle comparisons pin (float estimates are
    derived from it deterministically but reduction order may differ
    between backends).

    All-False mask rows (padding, or a query over zero campaigns)
    evaluate to 0: the union registers stay zero (estimate 0 via linear
    counting) and no slot can agree (an empty selection's masked min is
    the EMPTY sentinel, masked max is 0).
    """
    empty = jnp.uint32(EMPTY)
    sel = mask[:, :, None]
    # [Q, k]: min/max of each slot over the selected campaigns; a
    # selected-but-never-seen campaign contributes EMPTY to the max, so
    # any empty member forces disagreement — |∩| with an empty set is 0.
    sel_min = jnp.min(jnp.where(sel, mins[None], empty), axis=1)
    sel_max = jnp.max(jnp.where(sel, mins[None], jnp.uint32(0)), axis=1)
    agree = jnp.sum(((sel_min == sel_max) & (sel_min != empty))
                    .astype(jnp.int32), axis=1)
    union_regs = jnp.max(jnp.where(sel, registers[None], 0), axis=1)
    union = hll.estimate(union_regs).astype(jnp.float32)
    k = mins.shape[1]
    jacc = agree.astype(jnp.float32) / jnp.float32(k)
    est = jnp.where(overlap, union * jacc, union)
    return est, union, jacc, agree


class DispatchCounter:
    """Counts ``batch_query`` dispatches (the bench's ``<= ceil(Q/B)``
    acceptance is on this number)."""

    def __init__(self) -> None:
        self.dispatches = 0


def query_chunks(mins, registers, masks: np.ndarray,
                 overlap: np.ndarray, *, batch: int = DEFAULT_BATCH,
                 counter: DispatchCounter | None = None):
    """Evaluate Q queries in ``ceil(Q/batch)`` dispatches of ONE padded
    static shape (a single compile covers the whole storm).

    ``masks [Q, C] bool``, ``overlap [Q] bool`` (numpy).  Returns
    ``(est, union, jacc, agree)`` numpy arrays of length Q.
    """
    q = masks.shape[0]
    if q == 0:
        z = np.zeros(0, np.float32)
        return z, z.copy(), z.copy(), np.zeros(0, np.int32)
    batch = max(int(batch), 1)
    outs = []
    for off in range(0, q, batch):
        m = masks[off:off + batch]
        o = overlap[off:off + batch]
        rows = m.shape[0]
        if rows < batch:
            m = np.concatenate(
                [m, np.zeros((batch - rows, m.shape[1]), bool)])
            o = np.concatenate([o, np.zeros(batch - rows, bool)])
        res = batch_query(mins, registers, jnp.asarray(m),
                          jnp.asarray(o))
        if counter is not None:
            counter.dispatches += 1
        outs.append(tuple(np.asarray(x)[:rows] for x in res))
    est = np.concatenate([t[0] for t in outs])
    union = np.concatenate([t[1] for t in outs])
    jacc = np.concatenate([t[2] for t in outs])
    agree = np.concatenate([t[3] for t in outs])
    return est, union, jacc, agree
