"""Bounded, load-shedding reach-query server.

The serving contract mirrors the pub/sub layer's prime directive —
queries must never stall aggregation — extended with explicit admission
control:

- a **bounded queue** (``jax.reach.queue.depth``): a submit beyond the
  depth sheds the OLDEST pending query (freshest-first under overload —
  the newest queries are the ones whose answer is still wanted), the
  shed query is *answered* with ``{"shed": true}`` rather than dropped
  silently, and ``streambench_reach_shed_total`` counts it;
- **batched evaluation**: the worker drains everything queued (up to
  the batch cap) into ONE padded ``reach.query.batch_query`` dispatch,
  so thousands of concurrent queries amortize into a handful of device
  steps (``summary()['dispatches']`` is the bench's acceptance number);
- **per-query latency** (submit -> reply) lands in the
  ``streambench_reach_latency_ms`` histogram, which the
  ``jax.reach.slo.p99.ms`` objective (obs/slo.py) judges with the same
  two-window burn-rate machinery as the window-latency SLO;
- **epoch tagging**: every answer carries the epoch of the sketch
  state it was evaluated against.  The engine bumps the epoch on every
  restore, so a client can detect that an answer pre-dates a crash
  recovery — the chaos sweep asserts no post-resume answer carries a
  pre-resume epoch.

Query-path observability (ISSUE 11, all default-off):

- ``queryattr`` (:class:`~streambench_tpu.obs.queryattr.QueryLifecycle`,
  ``jax.obs.query``) stamps every query at admission / queue-exit /
  dispatch-submit / dispatch-complete / reply-write and decomposes the
  submit -> reply latency into queue/batch/dispatch/reply segments that
  sum to it; shed victims get a queue-only record reconciling exactly
  against ``streambench_reach_shed_total``; replies then carry a
  ``server`` block so clients can split network-vs-server time.
- ``spans`` (:class:`~streambench_tpu.obs.spans.SpanTracer`) receives
  per-batch ``query_assembly``/``query_dispatch``/``query_reply`` spans
  under the ``"query"`` category — the worker thread is its own lane in
  the perfetto trace, interleaved with the engine's ingest folds on the
  shared clock, which is what the contention ratio is computed from.
- ``flightrec`` gets the serving black-box records: rate-limited shed
  events and queue high-water marks, so a crash dump explains the
  query backlog.

State arrives by push (``update_state``): jax arrays are immutable, so
the engine hands over its current references under the GIL and the
worker evaluates against a consistent snapshot while folds continue.

Scale-out additions (ISSUE 14):

- **epoch + staleness stamps**: every push carries the host-ms stamp of
  when its planes were serialized (``shipped_ms``; defaults to push
  time for a writer-attached server), and every answer carries
  ``plane_epoch`` + ``staleness_ms`` so a client can bound how old the
  evidence behind an estimate is.  With ``max_staleness_ms`` set (read
  replicas), queries are SHED rather than answered against planes
  staler than the bound — including the not-yet-loaded-any-epoch case,
  where a replica must never block clients waiting for its first
  snapshot.
- **result cache** (:class:`~streambench_tpu.reach.cache.ReachQueryCache`):
  probes at admission under the live epoch, fills at evaluation, and is
  invalidated wholesale on every epoch bump.  Hits reply synchronously
  from the admission path — no queue, no dispatch — which is what the
  bench's cache-hit-p99 acceptance measures.
- **pluggable evaluator** (``query_fn``): the sharded engine passes its
  two-collective shard-local program
  (``ShardedReachEngine.query_callable``) so queries evaluate next to
  the shards; the default stays ``reach.query.batch_query``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from streambench_tpu.reach import query as rq
from streambench_tpu.utils.ids import now_ms

#: shared instrument name — obs/slo.py's reach objective get-or-creates
#: the SAME histogram geometry, so both sides see one instrument
LATENCY_HIST = "streambench_reach_latency_ms"

#: The fleet freshness hops (ISSUE 15), in pipeline order.  A reply's
#: age decomposes into: ``fold_lag`` (last fold into the planes ->
#: ship submit — the shipping-cadence wait), ``ship_wait`` (ship
#: submit -> record appended durable), ``tail_lag`` (record durable ->
#: this replica loaded it — the tailer poll), and ``serve`` (loaded ->
#: this reply written — how long the planes have been serving).  The
#: four sum EXACTLY to the fold-anchored ``staleness_ms`` the same
#: reply carries; writer-clock stamps are mapped into the replica's
#: clock first (obs/clock.py) so cross-host deltas are honest.
FRESHNESS_HOPS = ("fold_lag", "ship_wait", "tail_lag", "serve")

#: histogram family the per-hop samples land in (label ``hop=``, plus
#: ``hop="total"`` for the summed evidence age — the regress key)
FRESHNESS_HIST = "streambench_fleet_freshness_ms"


def freshness_hops(fresh: dict, reply_ms: "float | None" = None) -> dict:
    """One freshness decomposition from the stamp dict a fleet-mode
    state push carries (``folded_ms``/``submit_ms``/``shipped_ms`` in
    the writer clock — already offset-corrected by the replica — and
    the replica-local ``loaded_ms``).

    The stamp chain is clamped monotone (fold <= submit <= shipped <=
    loaded <= reply) so every hop is >= 0 and the partition contract
    holds by construction: ``sum(hops) == total`` exactly, where total
    is the reply's fold-anchored staleness.  A clamp only ever fires on
    sub-millisecond races or an uncorrected skew — the clock block the
    reply carries says which."""
    now = float(now_ms() if reply_ms is None else reply_ms)
    fm = float(fresh.get("folded_ms") or fresh.get("submit_ms")
               or fresh.get("shipped_ms") or now)
    sm = max(float(fresh.get("submit_ms") or fm), fm)
    tm = max(float(fresh.get("shipped_ms") or sm), sm)
    lm = max(float(fresh.get("loaded_ms") or tm), tm)
    now = max(now, lm)
    return {
        "fold_lag": sm - fm,
        "ship_wait": tm - sm,
        "tail_lag": lm - tm,
        "serve": now - lm,
        "total": now - fm,
    }


class ReachQueryServer:
    def __init__(self, campaigns: list[str], *, depth: int = 512,
                 batch: int = rq.DEFAULT_BATCH, registry=None,
                 hold: bool = False, queryattr=None, spans=None,
                 flightrec=None, cache=None,
                 max_staleness_ms: int | None = None, query_fn=None):
        self.campaigns = list(campaigns)
        self._index = {c: i for i, c in enumerate(self.campaigns)}
        self.depth = max(int(depth), 1)
        self.batch = max(int(batch), 1)
        self._q: deque = deque()
        self._cv = threading.Condition()
        # (mins, registers, k, R, epoch, shipped_ms, freshness) where
        # freshness is the fleet stamp dict (None off the fleet path)
        self._state = None
        self._hold = bool(hold)
        self._closed = False
        self.served = 0
        self.shed = 0
        self.shed_stale = 0      # subset of shed: staleness-bound sheds
        self.rejected = 0
        self.dispatches = 0
        # ISSUE 14: result cache, staleness bound (replicas), evaluator
        self._cache = cache
        self.max_staleness_ms = (None if max_staleness_ms is None
                                 else max(int(max_staleness_ms), 0))
        self._query_fn_custom = query_fn is not None
        self._query_fn = query_fn if query_fn is not None \
            else rq.batch_query
        # serving observability (ISSUE 11) — all None on the default
        # path: one attribute check per admission/batch, replies
        # byte-identical until jax.obs.query wires a QueryLifecycle
        self._queryattr = queryattr
        self._spans = spans
        self._flightrec = flightrec
        self.queue_high_water = 0
        self._fr_hw_recorded = 1     # next high-water worth a record
        self._fr_shed_last = 0.0     # monotonic stamp of last shed rec
        # fleet freshness (ISSUE 15): histograms are created lazily at
        # the first freshness-carrying reply so a fleet-off scrape
        # surface is unchanged; the flight-recorder high-water starts
        # at 1/8 of the staleness bound (unbounded servers: 1 s) and
        # doubles per record — log2-bounded trail, mirroring the
        # reach_queue_high_water pattern
        self._registry = registry
        self._fresh_hists = None
        self.freshness_high_water = 0.0
        self._fr_fresh_recorded = max(
            (self.max_staleness_ms or 0) / 8.0, 1000.0 / 8.0)
        self._warmed = False         # query kernel compiled (first push)
        self._lat_ring: deque = deque(maxlen=8192)  # ms, summary() only
        # raw (admit_ns, pop_ns) queue-wait intervals, monotonic clock:
        # CLOCK_MONOTONIC is system-wide on Linux, so a bench can
        # intersect a REPLICA's waits with the WRITER's ingest-busy
        # windows across process boundaries (the off-writer contention
        # measurement, ISSUE 14)
        self._wait_ring: deque = deque(maxlen=8192)
        self._served_t0: float | None = None
        self._served_t1: float | None = None
        self._c_shed = self._c_served = self._hist = None
        self._g_epoch = self._g_staleness = self._g_qps = None
        if registry is not None:
            self._c_shed = registry.counter(
                "streambench_reach_shed_total",
                "reach queries shed (oldest-first beyond queue depth, "
                "or past the staleness bound)")
            self._c_served = registry.counter(
                "streambench_reach_served_total",
                "reach queries answered with an estimate")
            self._hist = registry.histogram(
                LATENCY_HIST,
                "reach query latency, submit to reply (ms)")
            # replica-tier gauges (ISSUE 14): live on the writer too —
            # a writer-attached server is just a zero-staleness replica
            self._g_epoch = registry.gauge(
                "streambench_reach_replica_epoch",
                "epoch of the sketch planes this server answers against")
            self._g_staleness = registry.gauge(
                "streambench_reach_replica_staleness_ms",
                "age of the served planes: now minus their shipped "
                "stamp (bounded by the shipping cadence when healthy)")
            self._g_qps = registry.gauge(
                "streambench_reach_replica_qps",
                "served queries per second over the serving span")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="reach-query")
        self._thread.start()

    # -- state push ----------------------------------------------------
    def update_state(self, mins, registers, epoch: int,
                     shipped_ms: int | None = None,
                     freshness: dict | None = None) -> None:
        """Engine-side push of the current sketch planes (immutable jax
        arrays; the reference handoff is atomic under the GIL).  The
        FIRST push warms the padded query kernel on the caller's thread
        — the engine-warmup rule ("pre-compile every device program
        before announcing readiness") applied to the serving tier: an
        XLA compile racing a concurrently-dispatching ingest thread can
        starve for tens of seconds on a small host, and the first push
        happens at attach time, before traffic.

        ``shipped_ms``: host-ms stamp of when these planes were
        serialized — the replica staleness clock.  Writer-attached
        pushes omit it: their replies carry ``plane_epoch`` only
        (stamping a wall-clock staleness there would make replies
        nondeterministic for zero information — the planes ARE the
        writer's live state).

        ``freshness`` (fleet mode, ISSUE 15): the stamp dict
        (``folded_ms``/``submit_ms``/``shipped_ms`` writer-clock —
        offset-corrected by the replica — plus the local ``loaded_ms``
        and a ``clock`` estimate block).  When present, replies carry
        the per-hop decomposition and the staleness clock anchors at
        the FOLD watermark (the age of the evidence, which the hops sum
        to exactly) instead of the ship stamp."""
        if not self._warmed:
            self._warm(mins, registers)
        epoch = int(epoch)
        if self._cache is not None:
            # wholesale invalidation BEFORE the swap: a concurrent probe
            # may briefly miss under the new epoch, never hit stale
            self._cache.note_epoch(epoch)
        with self._cv:
            self._state = (mins, registers,
                           int(mins.shape[1]), int(registers.shape[1]),
                           epoch,
                           int(shipped_ms) if shipped_ms is not None
                           else None,
                           dict(freshness) if freshness else None)
            self._cv.notify()
        if self._g_epoch is not None:
            self._g_epoch.set(epoch)

    def _warm(self, mins, registers) -> None:
        try:
            C = len(self.campaigns)
            np.asarray(self._query_fn(
                mins, registers, np.zeros((self.batch, C), bool),
                np.zeros(self.batch, bool))[0])
            self._warmed = True
        except Exception:
            pass   # a failed warmup must not block serving; the first
            #        real batch compiles instead

    # -- staleness (replica serving bound) -----------------------------
    @staticmethod
    def _anchor(st) -> "float | None":
        """The stamp a state's age is measured from: the fleet fold
        watermark when the push carried freshness stamps (the hops sum
        to that age), else the shipped stamp, else None (live state)."""
        if st is None:
            return None
        fresh = st[6]
        if fresh is not None:
            anchor = (fresh.get("folded_ms") or fresh.get("submit_ms")
                      or fresh.get("shipped_ms"))
            if anchor is not None:
                return float(anchor)
        return float(st[5]) if st[5] is not None else None

    def staleness_ms(self, st=None) -> float | None:
        """Age of the served planes (vs their freshness anchor), or
        None when no push carried one (writer-attached: live state)."""
        st = st if st is not None else self._state
        anchor = self._anchor(st)
        if anchor is None:
            return None
        return float(max(now_ms() - anchor, 0))

    def _stale(self, st) -> bool:
        """True when answering against ``st`` would violate the
        staleness bound.  No bound configured -> never stale.  With a
        bound: no state yet, OR no stamp to prove freshness by, OR a
        stamp older than the bound -> stale (shed, don't block)."""
        if self.max_staleness_ms is None:
            return False
        anchor = self._anchor(st)
        return (anchor is None
                or (now_ms() - anchor) > self.max_staleness_ms)

    # -- fleet freshness ledger (ISSUE 15) -----------------------------
    def _freshness_block(self, st, reply_ms: "float | None" = None,
                         observe: bool = False) -> "dict | None":
        """The per-reply freshness decomposition, or None off the fleet
        path.  ``observe=True`` additionally lands one sample per hop
        (plus the total) in the ``streambench_fleet_freshness_ms``
        histograms and feeds the flight-recorder high-water trail —
        called once per SERVED reply so hop counts match the served
        count exactly."""
        fresh = st[6] if st is not None else None
        if fresh is None:
            return None
        hops = freshness_hops(fresh, reply_ms=reply_ms)
        block = {f"{hop}_ms": round(hops[hop], 1)
                 for hop in FRESHNESS_HOPS}
        # staleness == the hop sum BY CONSTRUCTION (same clamped chain,
        # same reply stamp) — the partition contract replies are pinned
        # against; rounding is per-hop, so the sum check carries
        # +-(len(hops) * 0.05) ms of slack at most
        block["staleness_ms"] = round(hops["total"], 1)
        clock = fresh.get("clock")
        if clock is not None:
            block["clock"] = {
                "offset_ms": clock.get("offset_ms"),
                "uncertainty_ms": clock.get("uncertainty_ms"),
                "applied": bool(clock.get("applied")),
            }
        if observe:
            self._observe_freshness(hops)
        return block

    def _observe_freshness(self, hops: dict) -> None:
        if self._registry is not None:
            if self._fresh_hists is None:
                self._fresh_hists = {
                    hop: self._registry.histogram(
                        FRESHNESS_HIST,
                        "end-to-end reply freshness by hop: the age of "
                        "the evidence behind a reach answer, decomposed "
                        "(ms)", lo=0.1, hi=1e8, growth=2 ** 0.125,
                        labels={"hop": hop})
                    for hop in FRESHNESS_HOPS + ("total",)}
            for hop, h in self._fresh_hists.items():
                h.observe(hops[hop])
        total = hops["total"]
        if total > self.freshness_high_water:
            self.freshness_high_water = total
        if (self._flightrec is not None
                and total >= 2 * self._fr_fresh_recorded):
            # doubling high-water: a staleness-shed storm leaves a
            # log2-bounded trail naming which hop grew (the crash-dump
            # reader's first question), without flooding the ring
            self._fr_fresh_recorded = total
            self._flightrec.record(
                "fleet_freshness_high_water",
                staleness_ms=round(total, 1),
                **{f"{hop}_ms": round(hops[hop], 1)
                   for hop in FRESHNESS_HOPS},
                max_staleness_ms=self.max_staleness_ms,
                shed_stale=self.shed_stale, served=self.served)

    def use_query_fn(self, fn) -> None:
        """Engine-side evaluator injection (``attach_reach``): the
        sharded engine routes evaluation through its shard-local
        two-collective program.  Respected only when the constructor
        didn't already receive an explicit ``query_fn``; must run
        BEFORE the first state push so the warmup compiles the right
        kernel."""
        if not self._query_fn_custom:
            self._query_fn = fn

    @property
    def epoch(self) -> int | None:
        st = self._state
        return st[4] if st is not None else None

    # -- admission -----------------------------------------------------
    def handle(self, msg: dict, reply) -> None:
        """The pub/sub query-verb hook: parse, admit (shedding the
        oldest beyond depth), never raise.  ``trace``/``sent_ms`` are
        the client-side trace id and send stamp the lifecycle records
        propagate (ignored when query obs is off)."""
        self.submit(msg.get("campaigns"), msg.get("op", "union"), reply,
                    query_id=msg.get("id"), trace=msg.get("trace"),
                    client_ms=msg.get("sent_ms"))

    def submit(self, campaigns, op, reply, query_id=None, trace=None,
               client_ms=None) -> bool:
        """Admit one query.  Returns False when it was rejected outright
        (malformed); shedding affects the *oldest* queued query, never
        the one being admitted.  A cache hit under the live epoch
        replies synchronously from THIS path — no queue, no dispatch."""
        t0_ns = time.perf_counter_ns()
        if op not in ("union", "overlap") or not isinstance(
                campaigns, (list, tuple)) or not campaigns:
            self.rejected += 1
            self._safe_reply(reply, {"error": "bad_request", "op": op,
                                     "id": query_id})
            return False
        idx = []
        for c in campaigns:
            i = self._index.get(c)
            if i is None:
                self.rejected += 1
                self._safe_reply(reply, {"error": "unknown_campaign",
                                         "campaign": c, "id": query_id})
                return False
            idx.append(i)
        if self._cache is not None:
            st = self._state
            if st is not None and not self._stale(st):
                entry = self._cache.get(st[4], idx, op)
                if entry is not None:
                    self._reply_cached(entry, st, reply, query_id,
                                       trace, client_ms, t0_ns)
                    return True
        rec = None
        if self._queryattr is not None:
            rec = self._queryattr.admit(trace=trace, qid=query_id,
                                        client_ms=client_ms)
        item = (idx, op == "overlap", reply, query_id,
                time.monotonic(), rec)
        victims = []
        with self._cv:
            self._q.append(item)
            pending = len(self._q)
            if pending > self.queue_high_water:
                self.queue_high_water = pending
            while len(self._q) > self.depth:
                victims.append(self._q.popleft())
                self.shed += 1
                if self._c_shed is not None:
                    self._c_shed.inc()
            self._cv.notify()
        if (self._flightrec is not None
                and self.queue_high_water >= 2 * self._fr_hw_recorded):
            # high-water doubled since the last record: log2(depth)
            # records max, so the bounded flight ring keeps room for
            # the feeders that matter at crash time
            self._fr_hw_recorded = self.queue_high_water
            self._flightrec.record(
                "reach_queue_high_water",
                high_water=self.queue_high_water, depth=self.depth,
                shed=self.shed, served=self.served)
        for old in victims:   # replies outside the lock: a slow socket
            self._reply_shed(old)
        if victims and self._flightrec is not None:
            now = time.monotonic()
            if now - self._fr_shed_last >= 1.0:
                # rate-limited (1 Hz): a sustained overload leaves a
                # trail without flooding the ring one record per victim
                self._fr_shed_last = now
                self._flightrec.record(
                    "reach_shed", shed_total=self.shed,
                    pending=self.pending(), depth=self.depth,
                    served=self.served)
        return True

    def _reply_cached(self, entry: dict, st, reply, query_id, trace,
                      client_ms, t0_ns: int) -> None:
        """One cache-hit reply, written synchronously from the admission
        path.  Leaves exactly one served lifecycle record (queryattr
        reconciliation holds) and lands in BOTH latency histograms —
        the main serving one and the cache-hit one the A/B reads."""
        payload = dict(entry)
        payload["id"] = query_id
        payload["cached"] = True
        # age evidence is REPLY-time state (cache.CACHEABLE_KEYS): a
        # hit carries the cached PLANE's current freshness, recomputed
        # now — never the fill-time hops frozen into the entry
        fresh_block = self._freshness_block(st, observe=True)
        if fresh_block is not None:
            payload["freshness"] = fresh_block
            payload["staleness_ms"] = fresh_block["staleness_ms"]
        else:
            stale = self.staleness_ms(st)
            if stale is not None:
                payload["staleness_ms"] = round(stale, 1)
        rec = None
        ql = self._queryattr
        if ql is not None:
            rec = ql.admit(trace=trace, qid=query_id,
                           client_ms=client_ms)
            now = time.perf_counter_ns()
            rec.t_exit = now
            payload["server"] = ql.server_block(rec, now, now)
        self._safe_reply(reply, payload)
        if rec is not None:
            now = time.perf_counter_ns()
            ql.note_reply(rec, now, now)
        lat_ms = (time.perf_counter_ns() - t0_ns) / 1e6
        self._lat_ring.append(lat_ms)
        if self._hist is not None:
            self._hist.observe(lat_ms)
        hh = getattr(self._cache, "hit_hist", None)
        if hh is not None:
            hh.observe(lat_ms)
        self.served += 1
        if self._c_served is not None:
            self._c_served.inc()
        now_m = time.monotonic()
        if self._served_t0 is None:
            self._served_t0 = now_m
        self._served_t1 = now_m

    def _reply_shed(self, item, reason: str | None = None,
                    st=None) -> None:
        """Answer one shed victim ``{"shed": true}``; with query obs on
        the reply also carries the queue-only server block (shed
        queries stamp too — the record count reconciles against the
        shed counter exactly).  Staleness sheds name their reason and
        the epoch/staleness evidence."""
        payload = {"shed": True, "id": item[3]}
        if reason is not None:
            payload["reason"] = reason
            payload["plane_epoch"] = st[4] if st is not None else None
            stale = self.staleness_ms(st) if st is not None else None
            if stale is not None:
                payload["staleness_ms"] = round(stale, 1)
        rec = item[5]
        if rec is not None:
            queue_ms = self._queryattr.note_shed(rec)
            block = {"queue_ms": round(queue_ms, 3)}
            if rec.trace is not None:
                block["trace"] = rec.trace
            payload["server"] = block
        self._safe_reply(item[2], payload)

    # -- hold/resume (bench storms: queue while held, then drain in
    # ceil(pending/batch) dispatches) ----------------------------------
    def pause(self) -> None:
        with self._cv:
            self._hold = True

    def resume(self) -> None:
        with self._cv:
            self._hold = False
            self._cv.notify()

    def pending(self) -> int:
        with self._cv:
            return len(self._q)

    # -- worker --------------------------------------------------------
    def _shed_items(self, items: list, reason: str, st) -> None:
        """Shed a popped batch (staleness bound): counted exactly like
        depth sheds — shed + served == sent stays an invariant."""
        for it in items:
            self.shed += 1
            self.shed_stale += 1
            if self._c_shed is not None:
                self._c_shed.inc()
            self._reply_shed(it, reason=reason, st=st)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._closed and (
                        self._hold or not self._q
                        or (self._state is None
                            and self.max_staleness_ms is None)):
                    # a staleness-bounded replica with no loaded epoch
                    # does NOT wait for one: it falls through and sheds
                    # (clients must never block on a replica's first
                    # snapshot load)
                    self._cv.wait(timeout=0.5)
                if self._closed and (not self._q
                                     or self._state is None):
                    # drain-at-close only works with state to evaluate
                    # against; without one, answer the stragglers as
                    # shed rather than spin
                    leftovers = list(self._q)
                    self._q.clear()
                    self.shed += len(leftovers)
                    if self._c_shed is not None:
                        # keep streambench_reach_shed_total == shed:
                        # close-time stragglers are sheds like any other
                        # (the lifecycle reconciliation depends on it)
                        for _ in leftovers:
                            self._c_shed.inc()
                else:
                    leftovers = None
                if leftovers is None and (
                        self._hold
                        or (self._state is None
                            and self.max_staleness_ms is None)):
                    continue
                items = state = None
                if leftovers is None:
                    items = [self._q.popleft()
                             for _ in range(min(len(self._q),
                                                self.batch))]
                    state = self._state
            if leftovers is not None:
                for it in leftovers:
                    self._reply_shed(it)
                return
            if self._stale(state):
                # staleness bound violated (or no epoch loaded yet):
                # shed rather than answer evidence older than the bound
                self._shed_items(items, reason="stale", st=state)
                continue
            try:
                self._evaluate(items, state)
            except Exception as e:   # a bad batch must not kill serving
                for it in items:
                    self._safe_reply(it[2], {"error": repr(e),
                                             "id": it[3]})

    def wait_intervals(self) -> list:
        """Raw [admit_ns, pop_ns] queue-wait intervals of evaluated
        queries (monotonic clock, bounded ring)."""
        return [list(t) for t in self._wait_ring]

    def _evaluate(self, items: list, state) -> None:
        ql = self._queryattr
        t_exit = time.perf_counter_ns()
        m_exit = time.monotonic_ns()
        for it in items:
            self._wait_ring.append((int(it[4] * 1e9), m_exit))
        recs = []
        if ql is not None:
            recs = [it[5] for it in items if it[5] is not None]
            for r in recs:
                r.t_exit = t_exit
        mins, registers, k, R, epoch, shipped_ms, fresh = state
        C = len(self.campaigns)
        mask = np.zeros((self.batch, C), bool)
        overlap = np.zeros(self.batch, bool)
        for row, (idx, is_overlap, _, _, _, _) in enumerate(items):
            mask[row, idx] = True
            overlap[row] = is_overlap
        t_submit = time.perf_counter_ns()
        est, union, jacc, _ = self._query_fn(
            mins, registers, mask, overlap)
        self.dispatches += 1
        # ALWAYS resolve the dispatch with block_until_ready before the
        # np.asarray conversions.  Under a concurrently-dispatching
        # ingest thread, np.asarray on a not-yet-ready array can starve
        # until the other thread quiesces (jax 0.4.37 CPU: the host-copy
        # wait loses to a busy dispatch stream indefinitely, while
        # block_until_ready waits bounded by the queue depth — measured
        # by the ISSUE 11 concurrent-ingest rung: 0.8 s vs 20+ s).
        import jax

        t_bd = time.perf_counter_ns()
        jax.block_until_ready((est, union, jacc))
        if ql is not None and ql.device_sample_due(self.dispatches):
            # dispatch-to-completion device time, 1-in-N sampled (the
            # OccupancySampler's cadence rule); off-sample batches pay
            # only the block they needed anyway
            ql.note_device_sample(
                (time.perf_counter_ns() - t_bd) / 1e6)
        est = np.asarray(est)
        union = np.asarray(union)
        jacc = np.asarray(jacc)
        t_done = time.perf_counter_ns()
        if ql is not None and recs:
            # contention accounting AFTER the block: any ingest fold
            # that overlapped these queue waits has completed by now,
            # so its measured busy window is already on record
            ql.note_queue_exit(recs)
        ub = rq.union_bound(R)
        ob = rq.overlap_bound(k, R)
        now = time.monotonic()
        # one wall stamp for the whole reply loop: every reply in the
        # batch carries the same age evidence, and the freshness hops
        # sum to the same staleness the reply states (fleet mode)
        now_wall = now_ms()
        fresh_block = fresh_hops_raw = None
        if fresh is not None:
            fresh_hops_raw = freshness_hops(fresh, reply_ms=now_wall)
            fresh_block = self._freshness_block(state, reply_ms=now_wall)
        if fresh_block is not None:
            staleness = fresh_block["staleness_ms"]
        else:
            staleness = (round(max(now_wall - shipped_ms, 0), 1)
                         if shipped_ms is not None else None)
        if self._served_t0 is None:
            self._served_t0 = now
        for row, (idx, is_overlap, reply, qid, t0, rec) in enumerate(
                items):
            lat_ms = (now - t0) * 1000.0
            self._lat_ring.append(lat_ms)
            if self._hist is not None:
                self._hist.observe(lat_ms)
            self.served += 1
            if self._c_served is not None:
                self._c_served.inc()
            op_name = "overlap" if is_overlap else "union"
            payload = {
                "op": op_name,
                "estimate": round(float(est[row]), 2),
                "union": round(float(union[row]), 2),
                "jaccard": round(float(jacc[row]), 5),
                # relative error bound: union is relative to the
                # estimate; overlap is relative to the UNION size (the
                # Jaccard estimator's natural scale)
                "bound": round(ob if is_overlap else ub, 5),
                "epoch": epoch,
                # explicit scale-out stamp (ISSUE 14): which planes
                # answered; replicas add how old their evidence was
                "plane_epoch": epoch,
                "id": qid,
            }
            if staleness is not None:
                payload["staleness_ms"] = staleness
            if fresh_block is not None:
                # fleet freshness ledger: one hop decomposition per
                # reply, observed into the {hop=} histograms so served
                # count == per-hop sample count exactly
                payload["freshness"] = fresh_block
                self._observe_freshness(fresh_hops_raw)
            if self._cache is not None:
                # cache the epoch-scoped answer (everything but the
                # per-query id and the reply-time age evidence —
                # cache.CACHEABLE_KEYS; put() is a no-op if the epoch
                # already moved)
                from streambench_tpu.reach.cache import CACHEABLE_KEYS

                self._cache.put(epoch, idx, op_name, {
                    key: payload[key] for key in CACHEABLE_KEYS})
            if rec is not None:
                # server-side decomposition (up to reply-write start):
                # the client splits round-trip into network-vs-server
                payload["server"] = ql.server_block(rec, t_submit,
                                                    t_done)
            self._safe_reply(reply, payload)
            if rec is not None:
                ql.note_reply(rec, t_submit, t_done)
        self._served_t1 = time.monotonic()
        if self._spans is not None:
            # the query lane: batch-level spans on THIS worker thread,
            # interleaved with the engine's ingest folds on the shared
            # perf_counter clock in one perfetto trace
            t_end = time.perf_counter_ns()
            n = len(items)
            self._spans.add("query_assembly", t_exit,
                            t_submit - t_exit, cat="query",
                            args={"queries": n})
            self._spans.add("query_dispatch", t_submit,
                            t_done - t_submit, cat="query",
                            args={"queries": n, "epoch": epoch})
            self._spans.add("query_reply", t_done, t_end - t_done,
                            cat="query", args={"queries": n})

    @staticmethod
    def _safe_reply(reply, data: dict) -> None:
        try:
            reply(data)
        except Exception:
            pass   # a dead subscriber must not kill the worker

    # -- reporting -----------------------------------------------------
    def summary(self) -> dict:
        lats = sorted(self._lat_ring)
        st = self._state
        out = {
            "served": self.served,
            "shed": self.shed,
            "rejected": self.rejected,
            "dispatches": self.dispatches,
            "batch": self.batch,
            "queue_depth": self.depth,
            "queue_high_water": self.queue_high_water,
        }
        if self.shed_stale:
            out["shed_stale"] = self.shed_stale
        if self.max_staleness_ms is not None:
            out["max_staleness_ms"] = self.max_staleness_ms
        if st is not None:
            out["plane_epoch"] = st[4]
            stale = self.staleness_ms(st)
            if stale is not None:
                out["staleness_ms"] = round(stale, 1)
        if self._fresh_hists is not None:
            # fleet freshness ledger (ISSUE 15): per-hop distributions
            # over every served reply + the doubling high-water; the
            # clock block is the LIVE state's offset evidence
            fr = {"hops": {hop: h.summary()
                           for hop, h in self._fresh_hists.items()},
                  "high_water_ms": round(self.freshness_high_water, 1)}
            clock = (st[6] or {}).get("clock") if st is not None else None
            if clock is not None:
                fr["clock"] = dict(clock)
            out["freshness"] = fr
        if self._cache is not None:
            out["cache"] = self._cache.summary()
        if self._queryattr is not None:
            out["query_obs"] = self._queryattr.summary()
        if lats:
            out["p50_ms"] = round(lats[len(lats) // 2], 2)
            out["p99_ms"] = round(lats[min(len(lats) - 1,
                                           int(len(lats) * 0.99))], 2)
        if (self._served_t0 is not None and self._served_t1 is not None
                and self._served_t1 > self._served_t0 and self.served):
            out["qps"] = round(
                self.served / (self._served_t1 - self._served_t0), 1)
        # live replica gauges (scraped between summary calls they hold
        # the last reading; the sampler collector calls summary per tick)
        if self._g_staleness is not None and "staleness_ms" in out:
            self._g_staleness.set(out["staleness_ms"])
        if self._g_qps is not None and "qps" in out:
            self._g_qps.set(out["qps"])
        return out

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._hold = False
            self._cv.notify()
        self._thread.join(timeout=10.0)
