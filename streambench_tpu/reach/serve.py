"""Bounded, load-shedding reach-query server.

The serving contract mirrors the pub/sub layer's prime directive —
queries must never stall aggregation — extended with explicit admission
control:

- a **bounded queue** (``jax.reach.queue.depth``): a submit beyond the
  depth sheds the OLDEST pending query (freshest-first under overload —
  the newest queries are the ones whose answer is still wanted), the
  shed query is *answered* with ``{"shed": true}`` rather than dropped
  silently, and ``streambench_reach_shed_total`` counts it;
- **batched evaluation**: the worker drains everything queued (up to
  the batch cap) into ONE padded ``reach.query.batch_query`` dispatch,
  so thousands of concurrent queries amortize into a handful of device
  steps (``summary()['dispatches']`` is the bench's acceptance number);
- **per-query latency** (submit -> reply) lands in the
  ``streambench_reach_latency_ms`` histogram, which the
  ``jax.reach.slo.p99.ms`` objective (obs/slo.py) judges with the same
  two-window burn-rate machinery as the window-latency SLO;
- **epoch tagging**: every answer carries the epoch of the sketch
  state it was evaluated against.  The engine bumps the epoch on every
  restore, so a client can detect that an answer pre-dates a crash
  recovery — the chaos sweep asserts no post-resume answer carries a
  pre-resume epoch.

State arrives by push (``update_state``): jax arrays are immutable, so
the engine hands over its current references under the GIL and the
worker evaluates against a consistent snapshot while folds continue.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from streambench_tpu.reach import query as rq

#: shared instrument name — obs/slo.py's reach objective get-or-creates
#: the SAME histogram geometry, so both sides see one instrument
LATENCY_HIST = "streambench_reach_latency_ms"


class ReachQueryServer:
    def __init__(self, campaigns: list[str], *, depth: int = 512,
                 batch: int = rq.DEFAULT_BATCH, registry=None,
                 hold: bool = False):
        self.campaigns = list(campaigns)
        self._index = {c: i for i, c in enumerate(self.campaigns)}
        self.depth = max(int(depth), 1)
        self.batch = max(int(batch), 1)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._state = None          # (mins, registers, k, R, epoch)
        self._hold = bool(hold)
        self._closed = False
        self.served = 0
        self.shed = 0
        self.rejected = 0
        self.dispatches = 0
        self._lat_ring: deque = deque(maxlen=8192)  # ms, summary() only
        self._served_t0: float | None = None
        self._served_t1: float | None = None
        self._c_shed = self._c_served = self._hist = None
        if registry is not None:
            self._c_shed = registry.counter(
                "streambench_reach_shed_total",
                "reach queries shed (oldest-first) beyond queue depth")
            self._c_served = registry.counter(
                "streambench_reach_served_total",
                "reach queries answered with an estimate")
            self._hist = registry.histogram(
                LATENCY_HIST,
                "reach query latency, submit to reply (ms)")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="reach-query")
        self._thread.start()

    # -- state push ----------------------------------------------------
    def update_state(self, mins, registers, epoch: int) -> None:
        """Engine-side push of the current sketch planes (immutable jax
        arrays; the reference handoff is atomic under the GIL)."""
        with self._cv:
            self._state = (mins, registers,
                           int(mins.shape[1]), int(registers.shape[1]),
                           int(epoch))
            self._cv.notify()

    @property
    def epoch(self) -> int | None:
        st = self._state
        return st[4] if st is not None else None

    # -- admission -----------------------------------------------------
    def handle(self, msg: dict, reply) -> None:
        """The pub/sub query-verb hook: parse, admit (shedding the
        oldest beyond depth), never raise."""
        self.submit(msg.get("campaigns"), msg.get("op", "union"), reply,
                    query_id=msg.get("id"))

    def submit(self, campaigns, op, reply, query_id=None) -> bool:
        """Admit one query.  Returns False when it was rejected outright
        (malformed); shedding affects the *oldest* queued query, never
        the one being admitted."""
        if op not in ("union", "overlap") or not isinstance(
                campaigns, (list, tuple)) or not campaigns:
            self.rejected += 1
            self._safe_reply(reply, {"error": "bad_request", "op": op,
                                     "id": query_id})
            return False
        idx = []
        for c in campaigns:
            i = self._index.get(c)
            if i is None:
                self.rejected += 1
                self._safe_reply(reply, {"error": "unknown_campaign",
                                         "campaign": c, "id": query_id})
                return False
            idx.append(i)
        item = (idx, op == "overlap", reply, query_id,
                time.monotonic())
        victims = []
        with self._cv:
            self._q.append(item)
            while len(self._q) > self.depth:
                victims.append(self._q.popleft())
                self.shed += 1
                if self._c_shed is not None:
                    self._c_shed.inc()
            self._cv.notify()
        for old in victims:   # replies outside the lock: a slow socket
            self._safe_reply(old[2], {"shed": True, "id": old[3]})
        return True

    # -- hold/resume (bench storms: queue while held, then drain in
    # ceil(pending/batch) dispatches) ----------------------------------
    def pause(self) -> None:
        with self._cv:
            self._hold = True

    def resume(self) -> None:
        with self._cv:
            self._hold = False
            self._cv.notify()

    def pending(self) -> int:
        with self._cv:
            return len(self._q)

    # -- worker --------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._closed and (
                        self._hold or not self._q
                        or self._state is None):
                    self._cv.wait(timeout=0.5)
                if self._closed and (not self._q
                                     or self._state is None):
                    # drain-at-close only works with state to evaluate
                    # against; without one, answer the stragglers as
                    # shed rather than spin
                    leftovers = list(self._q)
                    self._q.clear()
                    self.shed += len(leftovers)
                else:
                    leftovers = None
                if leftovers is None and (self._hold
                                          or self._state is None):
                    continue
                items = state = None
                if leftovers is None:
                    items = [self._q.popleft()
                             for _ in range(min(len(self._q),
                                                self.batch))]
                    state = self._state
            if leftovers is not None:
                for it in leftovers:
                    self._safe_reply(it[2], {"shed": True, "id": it[3]})
                return
            try:
                self._evaluate(items, state)
            except Exception as e:   # a bad batch must not kill serving
                for it in items:
                    self._safe_reply(it[2], {"error": repr(e),
                                             "id": it[3]})

    def _evaluate(self, items: list, state) -> None:
        mins, registers, k, R, epoch = state
        C = len(self.campaigns)
        mask = np.zeros((self.batch, C), bool)
        overlap = np.zeros(self.batch, bool)
        for row, (idx, is_overlap, _, _, _) in enumerate(items):
            mask[row, idx] = True
            overlap[row] = is_overlap
        est, union, jacc, _ = rq.batch_query(
            mins, registers, mask, overlap)
        self.dispatches += 1
        est = np.asarray(est)
        union = np.asarray(union)
        jacc = np.asarray(jacc)
        ub = rq.union_bound(R)
        ob = rq.overlap_bound(k, R)
        now = time.monotonic()
        if self._served_t0 is None:
            self._served_t0 = now
        for row, (idx, is_overlap, reply, qid, t0) in enumerate(items):
            lat_ms = (now - t0) * 1000.0
            self._lat_ring.append(lat_ms)
            if self._hist is not None:
                self._hist.observe(lat_ms)
            self.served += 1
            if self._c_served is not None:
                self._c_served.inc()
            self._safe_reply(reply, {
                "op": "overlap" if is_overlap else "union",
                "estimate": round(float(est[row]), 2),
                "union": round(float(union[row]), 2),
                "jaccard": round(float(jacc[row]), 5),
                # relative error bound: union is relative to the
                # estimate; overlap is relative to the UNION size (the
                # Jaccard estimator's natural scale)
                "bound": round(ob if is_overlap else ub, 5),
                "epoch": epoch,
                "id": qid,
            })
        self._served_t1 = time.monotonic()

    @staticmethod
    def _safe_reply(reply, data: dict) -> None:
        try:
            reply(data)
        except Exception:
            pass   # a dead subscriber must not kill the worker

    # -- reporting -----------------------------------------------------
    def summary(self) -> dict:
        lats = sorted(self._lat_ring)
        out = {
            "served": self.served,
            "shed": self.shed,
            "rejected": self.rejected,
            "dispatches": self.dispatches,
            "batch": self.batch,
            "queue_depth": self.depth,
        }
        if lats:
            out["p50_ms"] = round(lats[len(lats) // 2], 2)
            out["p99_ms"] = round(lats[min(len(lats) - 1,
                                           int(len(lats) * 0.99))], 2)
        if (self._served_t0 is not None and self._served_t1 is not None
                and self._served_t1 > self._served_t0 and self.served):
            out["qps"] = round(
                self.served / (self._served_t1 - self._served_t0), 1)
        return out

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._hold = False
            self._cv.notify()
        self._thread.join(timeout=10.0)
