"""Snapshot-shipped reach read replicas (ISSUE 14 tentpole (b)).

The reach-forecasting paper (PAPERS.md, arXiv 2502.14785) serves
audience-overlap queries at ad-platform scale by exploiting exactly
what PR 10 proved for our planes: sketches are TINY (a [C, k] uint32 +
[C, R] int32 pair is a few hundred KB at production settings) and
``merge`` is commutative/associative/idempotent — so a reader answering
against a shipped point-in-time copy is sound by construction, and N
stateless readers scale query throughput without the single writer
ever taking a read lock.

Wire format: the PR 10 base64 plane record
(``DurableDimensionStore.put_reach_sketches``), one JSON line per ship
carrying ``(epoch, mins, registers, watermark, campaigns, t)``.  The
WRITER side (:class:`SnapshotShipper`) appends one at a bounded cadence
(``jax.reach.ship.interval.ms``) — an epoch bump ships immediately, so
replicas learn about a restore within one poll.  The REPLICA side
(:class:`ReachReplica`) tails the log, loads the newest record into
device planes, and serves the existing pub/sub ``reach`` query verb
through a :class:`~streambench_tpu.reach.serve.ReachQueryServer` with:

- every reply stamped ``plane_epoch`` + ``staleness_ms`` (now minus the
  record's shipped stamp — bounded by cadence + poll when healthy, and
  *detectable by the client* when not);
- a hard staleness bound (``jax.reach.staleness.max.ms``): planes older
  than the bound — including "no epoch loaded yet" — SHED rather than
  answer, so a wedged shipper degrades loudly instead of serving
  arbitrarily old evidence;
- the (epoch, campaign-set) result cache wired in, invalidated
  wholesale on every epoch the tailer loads.

Run one per process::

    python -m streambench_tpu.reach.replica --ship <dir>/dimensions.log \
        --port 0 [--max-staleness-ms 10000] [--cache 4096]

The process prints ``replica: pubsub=<host>:<port>`` once serving
(harness/CI parse it) and one JSON stats line at exit.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time

import numpy as np

from streambench_tpu.utils.ids import now_ms

#: the shipped record kind (DurableDimensionStore.put_reach_sketches)
SHIP_KIND = "reach_sketch"

#: default hard staleness bound for replicas (ms): generous next to the
#: default 1 s shipping cadence, tight next to a wedged shipper
DEFAULT_MAX_STALENESS_MS = 10_000


def decode_ship_record(rec: dict) -> dict | None:
    """One parsed ship line -> planes dict, or None when torn/corrupt
    (the store's replay rule: keep the previous good record)."""
    if rec.get("kind") != SHIP_KIND:
        return None
    try:
        c = list(rec["c"])
        k, r = int(rec["k"]), int(rec["r"])
        mins = np.frombuffer(base64.b64decode(rec["mins"]),
                             np.uint32).reshape(len(c), k)
        regs = np.frombuffer(base64.b64decode(rec["regs"]),
                             np.int32).reshape(len(c), r)
    except (KeyError, ValueError, TypeError):
        return None
    return {"mins": mins, "registers": regs, "campaigns": c,
            "epoch": int(rec.get("epoch", 0)),
            "watermark": int(rec.get("wm", 0)),
            "shipped_ms": int(rec.get("t", 0)),
            # fleet freshness stamps + writer origin (ISSUE 15);
            # None on pre-fleet records
            "folded_ms": rec.get("fm"),
            "submit_ms": rec.get("sm"),
            "origin": rec.get("origin")}


class SnapshotShipper:
    """Writer-side cadence gate: serialize the current planes through
    ``DurableDimensionStore.put_reach_sketches`` at most once per
    ``interval_ms`` — except an epoch bump, which ships immediately
    (replicas must learn about a restore within one poll, not one
    cadence).  Attached via ``ReachSketchEngine.attach_shipper``; the
    engine calls :meth:`note_state` from its flush-cadence push path,
    so the writer is never blocked by readers — shipping is one host
    gather + one appended line, and only at the cadence.

    This is the FULL-plane path — O(C) gather + bytes per tick.  The
    O(ΔC) dirty-row path (ISSUE 18) is :class:`~streambench_tpu.reach.
    deltaship.DeltaShipper`, a drop-in subclass selected by
    ``jax.reach.ship.delta``."""

    #: engines enable host-side dirty-row tracking for shippers that
    #: declare this (deltaship.DeltaShipper overrides to True)
    wants_dirty = False
    mode = "full"

    def __init__(self, store, campaigns: list[str],
                 interval_ms: int = 1000, registry=None,
                 origin: dict | None = None):
        self.store = store
        self.campaigns = list(campaigns)
        self.interval_ms = max(int(interval_ms), 1)
        self.ships = 0
        self._last_ship = 0.0      # monotonic
        self._last_epoch: int | None = None
        # per-tick ship cost evidence (ISSUE 18): what the gather +
        # encode actually cost, per record and cumulative — the obs
        # surface the delta path is judged against
        self.bytes_last = 0
        self.rows_last = 0
        self.ship_ms_last = 0.0
        self.bytes_total = 0
        self.rows_total = 0
        self.ship_ms_total = 0.0
        # fleet origin metadata (ISSUE 15): the writer's pub/sub
        # endpoint + pid, stamped into every shipped record so a
        # replica can (a) ping it for the clock-offset estimate and
        # (b) attribute the record in the merged fleet view
        self.origin = dict(origin) if origin else None
        self._g_ships = None
        self._g_bytes = self._g_rows = self._g_ms = None
        if registry is not None:
            self._g_ships = registry.counter(
                "streambench_reach_ship_total",
                "reach snapshot records shipped to the replica log")
            self._g_bytes = registry.gauge(
                "streambench_ship_bytes_per_tick",
                "encoded bytes of the last shipped record")
            self._g_rows = registry.gauge(
                "streambench_ship_rows_per_tick",
                "plane rows carried by the last shipped record")
            self._g_ms = registry.gauge(
                "streambench_ship_ms_per_tick",
                "wall ms of the last ship (gather + encode + append)")

    def due(self, epoch: int) -> bool:
        """Would a ship happen now?  (The engine checks this BEFORE
        pulling the watermark scalar off device — no sync on the
        not-yet-due flushes between cadence ticks.)"""
        return (self._last_epoch != int(epoch)
                or (time.monotonic() - self._last_ship) * 1000.0
                >= self.interval_ms)

    def note_state(self, mins, registers, epoch: int,
                   watermark: int = 0, force: bool = False,
                   folded_ms: int | None = None,
                   dirty_rows=None) -> bool:
        """Maybe ship; returns True when a record was written.
        ``force`` bypasses the cadence — the writer's close-time ship
        AND the restart-path ship (engine restore / shipper re-attach
        after a supervised crash): replicas must converge on the live
        planes immediately, not at the next cadence tick.

        ``folded_ms``: wall stamp of the last fold into these planes
        (the engine's ``_fold_wall_ms``) — the fold-anchored end of the
        freshness ledger; the ship-submit stamp is taken here.

        ``dirty_rows`` (ISSUE 18): the rows touched since the last
        ship.  Ignored here — the full-plane path always ships all of
        C; the DeltaShipper subclass is the consumer."""
        now = time.monotonic()
        epoch = int(epoch)
        if (not force and self._last_epoch == epoch
                and (now - self._last_ship) * 1000.0 < self.interval_ms):
            return False
        t0 = time.perf_counter()
        submit_ms = now_ms()
        mins = np.asarray(mins)
        nbytes = self.store.put_reach_sketches(
            mins, np.asarray(registers), self.campaigns,
            epoch, watermark=int(watermark),
            folded_ms=(int(folded_ms) if folded_ms is not None
                       else submit_ms),
            submit_ms=submit_ms, origin=self.origin)
        self._mark_shipped(now, epoch, int(nbytes or 0),
                           int(mins.shape[0]),
                           (time.perf_counter() - t0) * 1e3)
        return True

    def _mark_shipped(self, now: float, epoch: int, nbytes: int,
                      rows_n: int, ship_ms: float) -> None:
        """One record hit the log: advance the cadence gate and the
        per-tick cost evidence (counters + gauges)."""
        self._last_ship = now
        self._last_epoch = epoch
        self.ships += 1
        self.bytes_last, self.rows_last = nbytes, rows_n
        self.ship_ms_last = ship_ms
        self.bytes_total += nbytes
        self.rows_total += rows_n
        self.ship_ms_total += ship_ms
        if self._g_ships is not None:
            self._g_ships.inc()
        if self._g_bytes is not None:
            self._g_bytes.set(nbytes)
            self._g_rows.set(rows_n)
            self._g_ms.set(ship_ms)

    def summary(self) -> dict:
        return {"ships": self.ships, "interval_ms": self.interval_ms,
                "epoch": self._last_epoch, "mode": self.mode,
                "bytes_per_tick": self.bytes_last,
                "rows_per_tick": self.rows_last,
                "ship_ms_per_tick": round(self.ship_ms_last, 3),
                "bytes_total": self.bytes_total,
                "rows_total": self.rows_total,
                "ship_ms_total": round(self.ship_ms_total, 3)}


class ShipLogTailer:
    """Incremental reader of the ship log: each ``poll`` consumes newly
    appended complete lines and returns the NEWEST decodable reach
    record among them (a replica only ever wants the latest planes; a
    torn tail line stays buffered until its newline lands)."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._carry = b""
        self.records_seen = 0

    def poll(self) -> dict | None:
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                data = f.read()
        except FileNotFoundError:
            return None
        if not data:
            return None
        self._pos += len(data)
        data = self._carry + data
        nl = data.rfind(b"\n") + 1
        self._carry = data[nl:]
        newest = None
        for line in data[:nl].splitlines():
            line = line.strip()
            if not line or b'"reach_sketch"' not in line:
                continue
            try:
                rec = decode_ship_record(json.loads(line))
            except json.JSONDecodeError:
                continue
            if rec is not None:
                newest = rec
                self.records_seen += 1
        return newest


class ReachReplica:
    """One stateless read replica: ship-log tailer -> local epoch-
    stamped planes -> pub/sub ``reach`` verb.

    The pub/sub endpoint starts serving IMMEDIATELY; until the first
    record loads, every query is shed with ``reason: "stale"`` (the
    not-yet-loaded-an-epoch case of the staleness bound) — a replica
    never blocks clients on its own bootstrap.
    """

    def __init__(self, ship_path: str, *, host: str = "127.0.0.1",
                 port: int = 0, poll_ms: int = 200,
                 max_staleness_ms: int = DEFAULT_MAX_STALENESS_MS,
                 cache_capacity: int = 4096, depth: int = 512,
                 batch: int = 64, registry=None, queryattr=None,
                 fleet: bool = False, spans=None, flightrec=None):
        from streambench_tpu.dimensions.pubsub import PubSubServer
        from streambench_tpu.obs import MetricsRegistry

        # lazy: deltaship imports this module (SnapshotShipper)
        from streambench_tpu.reach.deltaship import ChainTailer

        self.ship_path = ship_path
        self.poll_ms = max(int(poll_ms), 1)
        self.max_staleness_ms = int(max_staleness_ms)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        # delta-aware chain tailer (ISSUE 18): folds dirty-row delta
        # records between bases, resyncs from the newest base on any
        # gap/damage; over a base-only (full-ship) log it behaves
        # exactly like the legacy ShipLogTailer
        self._tailer = ChainTailer(ship_path)
        self._depth = depth
        self._batch = batch
        self._cache_capacity = int(cache_capacity)
        self._queryattr = queryattr
        # fleet freshness (ISSUE 15): pass the shipped records' stamp
        # chain through to the server (replies then carry the hop
        # decomposition) and estimate the clock offset to the writer's
        # pub/sub origin so cross-host stamp deltas are honest
        self.fleet = bool(fleet)
        self._spans = spans
        self._flightrec = flightrec
        self.clock: dict | None = None        # last offset estimate
        self._clock_origin: str | None = None  # addr it was made against
        self.server = None            # built at first record (campaigns)
        self.cache = None
        self.epoch_loads = 0
        self.plane_loads = 0
        self.shed_before_load = 0
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.pubsub = PubSubServer(host=host, port=port)
        self.pubsub.register_query("reach", self._handle)
        self._poller = threading.Thread(target=self._poll_loop,
                                        daemon=True,
                                        name="reach-replica-poll")

    # -- serving -------------------------------------------------------
    @property
    def address(self) -> tuple[str, int]:
        return self.pubsub.address

    def _handle(self, msg: dict, reply) -> None:
        srv = self.server
        if srv is None:
            # no epoch loaded yet: shed, never block on bootstrap
            self.shed_before_load += 1
            reply({"shed": True, "reason": "stale", "plane_epoch": None,
                   "id": msg.get("id")})
            return
        srv.handle(msg, reply)

    # -- clock-domain correction (fleet mode) --------------------------
    def _sync_clock(self, origin: dict | None) -> None:
        """One midpoint-method offset estimate against the writer's
        pub/sub origin, refreshed when the origin address changes.  A
        failed sync (writer gone, port closed) records ``applied:
        False`` — raw stamps are then used as-is, never corrected by a
        guess."""
        addr = (origin or {}).get("addr")
        if not addr or addr == self._clock_origin:
            return
        from streambench_tpu.obs import clock as obs_clock

        self._clock_origin = addr
        try:
            host, port = addr.rsplit(":", 1)
            self.clock = obs_clock.sync_pubsub(host, int(port), n=8,
                                               timeout_s=2.0)
        except (OSError, ValueError) as e:
            self.clock = {"offset_ms": 0.0, "applied": False,
                          "error": repr(e), "endpoint": addr}

    def _freshness(self, rec: dict, loaded_ms: int) -> dict | None:
        """The stamp dict a fleet-mode state push carries: writer-clock
        stamps mapped into this replica's clock (when the offset
        estimate passed the jitter gate) + the local load stamp."""
        if not self.fleet:
            return None
        from streambench_tpu.obs import clock as obs_clock

        def local(stamp):
            return (None if stamp is None
                    else obs_clock.to_local_ms(stamp, self.clock))

        out = {"folded_ms": local(rec.get("folded_ms")),
               "submit_ms": local(rec.get("submit_ms")),
               "shipped_ms": local(rec.get("shipped_ms")),
               "loaded_ms": int(loaded_ms)}
        if self.clock is not None:
            out["clock"] = dict(self.clock)
        return out

    # -- plane loading -------------------------------------------------
    def _load(self, rec: dict) -> None:
        import jax.numpy as jnp

        from streambench_tpu.reach.cache import ReachQueryCache
        from streambench_tpu.reach.serve import ReachQueryServer

        if self.fleet:
            # outside the lock: a slow/failed ping must not stall the
            # admission path's server lookup
            self._sync_clock(rec.get("origin"))
        with self._lock:
            if self.server is None:
                self.cache = (ReachQueryCache(self._cache_capacity,
                                              registry=self.registry)
                              if self._cache_capacity > 0 else None)
                self.server = ReachQueryServer(
                    rec["campaigns"], depth=self._depth,
                    batch=self._batch, registry=self.registry,
                    cache=self.cache,
                    max_staleness_ms=self.max_staleness_ms,
                    queryattr=self._queryattr, spans=self._spans,
                    flightrec=self._flightrec)
            prev = self.server.epoch
            # jnp.array (copy=True): the chain tailer owns and mutates
            # its folded plane arrays across polls — the served planes
            # must never alias them
            self.server.update_state(
                jnp.array(rec["mins"]), jnp.array(rec["registers"]),
                rec["epoch"], shipped_ms=rec["shipped_ms"],
                freshness=self._freshness(rec, now_ms()))
            self.plane_loads += 1
            if prev != rec["epoch"]:
                self.epoch_loads += 1

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            rec = self._tailer.poll()
            if rec is not None:
                self._load(rec)
            self._stop.wait(self.poll_ms / 1000.0)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "ReachReplica":
        self.pubsub.start()
        self._poller.start()
        return self

    def poll_once(self) -> bool:
        """Synchronous single poll (tests drive the tailer directly)."""
        rec = self._tailer.poll()
        if rec is None:
            return False
        self._load(rec)
        return True

    def summary(self) -> dict:
        out = {
            "ship_path": self.ship_path,
            "poll_ms": self.poll_ms,
            "max_staleness_ms": self.max_staleness_ms,
            "plane_loads": self.plane_loads,
            "epoch_loads": self.epoch_loads,
            "shed_before_load": self.shed_before_load,
            # chain-tailer evidence (ISSUE 18): bases/deltas applied,
            # gaps + damaged records survived, resyncs taken
            "tailer": self._tailer.stats(),
        }
        if self.fleet:
            out["fleet"] = True
            if self.clock is not None:
                out["clock"] = dict(self.clock)
        if self.server is not None:
            out["serve"] = self.server.summary()
        return out

    def close(self) -> None:
        self._stop.set()
        if self._poller.is_alive():
            self._poller.join(timeout=10.0)
        self.pubsub.close()
        if self.server is not None:
            self.server.close()


def main(argv: list[str] | None = None) -> int:
    import argparse
    import signal

    from streambench_tpu.utils.platform import pin_jax_platform

    pin_jax_platform()

    ap = argparse.ArgumentParser(
        prog="streambench-reach-replica", description=__doc__)
    ap.add_argument("--ship", required=True,
                    help="ship log path (the writer store's "
                         "dimensions.log) or its directory")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--poll-ms", type=int, default=200)
    ap.add_argument("--max-staleness-ms", type=int,
                    default=DEFAULT_MAX_STALENESS_MS)
    ap.add_argument("--cache", type=int, default=4096,
                    help="query-result cache capacity (0 disables)")
    ap.add_argument("--depth", type=int, default=512)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--duration", type=float, default=None,
                    help="seconds to serve (default: until SIGTERM)")
    ap.add_argument("--dump-queue-waits", action="store_true",
                    help="include raw queue-wait intervals in the exit "
                         "stats (the bench's off-writer contention "
                         "measurement reads them)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet observability (ISSUE 15): replies carry "
                         "the freshness hop decomposition, the clock "
                         "offset to the writer origin is estimated, and "
                         "--metrics-dir gets this role's metrics.jsonl/"
                         "trace/flight files for the FleetCollector")
    ap.add_argument("--metrics-dir", default=None,
                    help="workdir for this replica's metrics.jsonl + "
                         "trace_<pid>.json + flight dumps (fleet mode)")
    ap.add_argument("--metrics-interval-ms", type=int, default=1000)
    ap.add_argument("--pid-file", default=None,
                    help="pidfile path (pids/replica_<n>); written as "
                         "'<pid> <starttime>' so liveness checks survive "
                         "pid recycling; REFUSES to start when the file "
                         "names a live process")
    args = ap.parse_args(argv)

    if args.pid_file:
        from streambench_tpu.utils.pidfile import (
            acquire_pidfile,
            pidfile_alive,
            release_pidfile,
        )

        if acquire_pidfile(args.pid_file) is None:
            print(f"replica: refusing to start — {args.pid_file} names "
                  f"live pid {pidfile_alive(args.pid_file)}", flush=True)
            return 1

    ship = args.ship
    if os.path.isdir(ship):
        from streambench_tpu.dimensions.store import LOG_NAME

        ship = os.path.join(ship, LOG_NAME)

    sampler = spans = flightrec = None
    registry = None
    if args.metrics_dir:
        from streambench_tpu.obs import (
            FlightRecorder,
            MetricsRegistry,
            MetricsSampler,
            SpanTracer,
        )

        os.makedirs(args.metrics_dir, exist_ok=True)
        registry = MetricsRegistry()
        sampler = MetricsSampler(
            os.path.join(args.metrics_dir, "metrics.jsonl"),
            interval_ms=args.metrics_interval_ms, registry=registry,
            role="replica")
        if args.fleet:
            spans = SpanTracer(registry=registry)
            flightrec = FlightRecorder(args.metrics_dir)
            flightrec.span_source = spans.tail

    rep = ReachReplica(ship, host=args.host, port=args.port,
                       poll_ms=args.poll_ms,
                       max_staleness_ms=args.max_staleness_ms,
                       cache_capacity=args.cache, depth=args.depth,
                       batch=args.batch, registry=registry,
                       fleet=args.fleet, spans=spans,
                       flightrec=flightrec).start()
    if sampler is not None:
        # the replica's side of the fleet story: every snapshot carries
        # the SAME "reach_query" block shape the writer journals, so
        # the FleetCollector and `obs fleet` render both roles from one
        # schema; "replica" adds the tailer's own counters
        def _collect(rec, dt_s):
            rec["reach_query"] = (rep.server.summary()
                                  if rep.server is not None else None)
            rec["replica"] = {
                "plane_loads": rep.plane_loads,
                "epoch_loads": rep.epoch_loads,
                "shed_before_load": rep.shed_before_load,
            }
            if rep.clock is not None:
                rec["clock"] = dict(rep.clock)

        sampler.add_collector(_collect)
        sampler.start()
    host, port = rep.address
    fleet_note = " fleet=on" if args.fleet else ""
    print(f"replica: pubsub={host}:{port} ship={ship} "
          f"max_staleness_ms={args.max_staleness_ms} "
          f"cache={args.cache}{fleet_note}", flush=True)

    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    t0 = now_ms()
    if args.duration is not None:
        done.wait(args.duration)
    else:
        done.wait()
    stats = rep.summary()
    stats["wall_s"] = round((now_ms() - t0) / 1000.0, 2)
    if args.dump_queue_waits and rep.server is not None:
        stats["queue_waits_ns"] = rep.server.wait_intervals()
    rep.close()
    if spans is not None:
        spans.dump(os.path.join(args.metrics_dir,
                                f"trace_{os.getpid()}.json"),
                   run="reach-replica")
    if flightrec is not None and len(flightrec):
        # the replica's black box: staleness high-water / shed trail
        # (dumped at exit so a storm postmortem has the evidence even
        # when the process itself ended cleanly)
        flightrec.dump("replica_exit")
    if sampler is not None:
        sampler.close(final=stats)
    if args.pid_file:
        release_pidfile(args.pid_file)
    print(json.dumps(stats), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
