"""Reach query-result cache keyed by (epoch, canonical campaign-set,
kind) — ISSUE 14 tentpole (c).

Reach answers are pure functions of the sketch planes, and the planes
are versioned by the serving epoch: two queries over the same campaign
set against the same epoch MUST produce identical answers.  That makes
an exact result cache sound with one rule — an epoch bump invalidates
everything, wholesale (``note_epoch``), because entries keyed under an
older epoch can never be served again and would only hold memory.

The key canonicalizes the campaign selection (sorted index tuple), so
``{A, B}`` and ``{B, A}`` share an entry, and carries the query kind
(union vs overlap).  Eviction is plain LRU under a bounded capacity.

Instrumented for the serving tier's A/B:
``streambench_reach_cache_{hits,misses,evictions}_total`` counters plus
a hit-latency histogram (``streambench_reach_cache_hit_ms``: admission
-> reply of answers served straight from the cache, never touching the
queue or the device) — the bench's "cache-hit p99 >= 10x below the
cache-miss p99" acceptance reads these.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

#: shared instrument name — the serve layer observes hit latencies here
HIT_LATENCY_HIST = "streambench_reach_cache_hit_ms"

#: The reply keys that are pure functions of (epoch, campaign-set,
#: kind) and therefore sound to cache.  Everything else is REPLY-TIME
#: state and must be recomputed on every hit: the per-query ``id``, and
#: the age evidence — ``staleness_ms`` and the fleet ``freshness`` hop
#: decomposition (ISSUE 15).  A hit served with the FILL-time freshness
#: block would claim the answer is as fresh as it was minutes ago; the
#: serve layer recomputes both against the live plane stamps instead.
CACHEABLE_KEYS = ("op", "estimate", "union", "jaccard", "bound",
                  "epoch", "plane_epoch")


class ReachQueryCache:
    """Bounded LRU of reach answers, epoch-scoped.

    Thread-safe: admission threads probe (``get``) while the worker
    thread fills (``put``) and the state-push path invalidates
    (``note_epoch``).
    """

    def __init__(self, capacity: int = 4096, registry=None):
        self.capacity = max(int(capacity), 1)
        self._od: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self._epoch: int | None = None
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self._c_hits = self._c_misses = self._c_evict = None
        self.hit_hist = None
        if registry is not None:
            self._c_hits = registry.counter(
                "streambench_reach_cache_hits_total",
                "reach queries answered from the (epoch, campaign-set) "
                "result cache")
            self._c_misses = registry.counter(
                "streambench_reach_cache_misses_total",
                "reach cache probes that fell through to a device "
                "dispatch")
            self._c_evict = registry.counter(
                "streambench_reach_cache_evictions_total",
                "reach cache LRU evictions (capacity pressure; epoch "
                "invalidations are counted separately)")
            self.hit_hist = registry.histogram(
                HIT_LATENCY_HIST,
                "admission -> reply latency of cache-hit reach answers "
                "(ms)", lo=0.001, hi=1e5)

    @staticmethod
    def key(idx, op: str) -> tuple:
        """Canonical campaign-set key: sorted index tuple + kind."""
        return (tuple(sorted(int(i) for i in idx)), str(op))

    # ------------------------------------------------------------------
    def note_epoch(self, epoch: int) -> None:
        """The serving epoch moved: drop EVERY entry.  Old-epoch answers
        can never be served again (lookups carry the live epoch), so
        wholesale invalidation is both the correctness story the tests
        pin and the memory bound."""
        epoch = int(epoch)
        with self._lock:
            if self._epoch == epoch:
                return
            if self._od:
                self.invalidations += 1
            self._epoch = epoch
            self._od.clear()

    def get(self, epoch: int, idx, op: str) -> dict | None:
        """Probe for a cached answer under the CURRENT epoch; counts the
        hit/miss either way.  Returns the stored payload dict (shared,
        treat as immutable) or None."""
        k = self.key(idx, op)
        with self._lock:
            hit = None
            if self._epoch == int(epoch):
                hit = self._od.get(k)
                if hit is not None:
                    self._od.move_to_end(k)
            if hit is None:
                self.misses += 1
            else:
                self.hits += 1
        if hit is None:
            if self._c_misses is not None:
                self._c_misses.inc()
        elif self._c_hits is not None:
            self._c_hits.inc()
        return hit

    def put(self, epoch: int, idx, op: str, payload: dict) -> None:
        """Store one answer computed against ``epoch``; ignored when the
        cache has already moved past it (a worker racing an epoch bump
        must never resurrect stale results — the invalidation test)."""
        k = self.key(idx, op)
        evicted = 0
        with self._lock:
            if self._epoch != int(epoch):
                return
            self._od[k] = payload
            self._od.move_to_end(k)
            while len(self._od) > self.capacity:
                self._od.popitem(last=False)
                self.evictions += 1
                evicted += 1
        if evicted and self._c_evict is not None:
            self._c_evict.inc(evicted)

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def summary(self) -> dict:
        with self._lock:
            hits, misses = self.hits, self.misses
            out = {
                "capacity": self.capacity,
                "entries": len(self._od),
                "hits": hits,
                "misses": misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "epoch": self._epoch,
            }
        probes = hits + misses
        out["hit_ratio"] = round(hits / probes, 4) if probes else 0.0
        return out
