"""Multichip scale-out bench: oracle-verified events/s + MEASURED
collective costs for the sharded engines on a virtual host mesh.

The reference scales by a keyed network shuffle (Storm
``fieldsGrouping("campaign_id")``, Flink ``keyBy(0)``); our TPU-native
answer — campaign-sharded state with the batch gathered over the data
axis (``parallel/{mesh,sharded,sketches}.py``) — was tested for
bit-equality but had ZERO performance numbers (every ``MULTICHIP_r0*``
artifact was a dry-run status with an empty tail).  This bench produces
them, honestly:

- **events/s, oracle-verified**: the sharded exact-count engine runs a
  real catchup against the golden model (``check_correct``), and the
  sharded HLL engine is checked for Redis-state equality with the
  single-device HLL engine on the same journal.
- **per-dispatch collective costs, from the compiled program**: op
  counts and payload bytes parsed out of the optimized HLO
  (``parallel.collectives``) for all four scan arms —
  {unpacked, packed} x {per-batch, hoisted} — plus a timed
  dispatch for each arm.

What a virtual host mesh (``--xla_force_host_platform_device_count``)
CAN and CANNOT show, stated up front because the artifact is committed:
it proves sharding semantics (oracle equality) and the STRUCTURE of the
communication (how many collectives of how many bytes the compiled
program issues per dispatch — the thing ICI latency multiplies), but
every "device" here is a thread slice of one CPU core, so the timed
ev/s measures compute slowdown from emulation, NOT interconnect
bandwidth; expect ev/s to FALL as n_devices rises on this host.  The
collective table is the transferable result; the ev/s ladder is the
honesty check that nothing pathological happens to wall time.

Budget: the whole run (all rungs, all engines) self-caps at
``STREAMBENCH_BENCH_BUDGET_S`` (default 840 s < the 870 s driver kill),
skipping remaining rungs when the envelope runs out — every completed
rung emits a compact (<= 4096 B) single-line JSON on stdout so a
tail-truncating consumer always ends on a parseable line (the PR 6
emission rules).

Usage:
    python bench_multichip.py                    # full: n in {1, 2, 8}
    python bench_multichip.py --smoke            # CI: n in {1, 2}, tiny
    python bench_multichip.py --artifact MULTICHIP_r06.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

COMPACT_LINE_MAX = 4096
REPO = os.path.dirname(os.path.abspath(__file__))

_T0 = time.monotonic()


def log(msg: str) -> None:
    print(f"[{time.monotonic() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def compact_line(obj: dict) -> str:
    """One bounded stdout line (the PR 6 truncation-proof contract):
    strip detail fields until the JSON fits COMPACT_LINE_MAX."""
    def dump(o):
        return json.dumps(o, separators=(",", ":"))

    line = dump(obj)
    if len(line) <= COMPACT_LINE_MAX:
        return line
    obj = json.loads(line)  # deep copy before mutating
    # progressively shed: per-arm by_kind, step arms, per-arm ms, runs'
    # hll + sliding/session detail blocks — the scan collective table
    # is the last thing to go (the hoist-ops headlines stay)
    for strip in ("by_kind", "device_wait_ms", "step",
                  "straggler_spread_ms", "ms_per_dispatch", "hll",
                  "sliding_scan", "session_scan"):
        for run in obj.get("runs", []):
            if strip in ("step", "hll", "sliding_scan", "session_scan"):
                run.pop(strip, None)
            else:
                for arm in (run.get("scan") or {}).values():
                    if isinstance(arm, dict):
                        arm.pop(strip, None)
        line = dump(obj)
        if len(line) <= COMPACT_LINE_MAX:
            return line
    obj.pop("runs", None)
    return dump(obj)


# ----------------------------------------------------------------------
# worker: one n_devices rung in its own process (the virtual device
# count must be pinned before jax initializes a backend)
# ----------------------------------------------------------------------

def _mesh_shape(n: int) -> tuple:
    """(data, campaign) for an n-device rung: campaign axis 2 once there
    are enough devices to shard both ways, else pure data parallelism."""
    return (n // 2, 2) if n >= 4 and n % 2 == 0 else (n, 1)


def _worker(args) -> int:
    # Env was pinned by the parent (JAX_PLATFORMS=cpu + device-count
    # flag) BEFORE this process imported jax — same discipline as
    # __graft_entry__._pin_virtual_devices.
    import random

    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from streambench_tpu.config import default_config
    from streambench_tpu.datagen import gen
    from streambench_tpu.engine import StreamRunner
    from streambench_tpu.engine.sketches import HLLDistinctEngine
    from streambench_tpu.io.fakeredis import FakeRedisStore
    from streambench_tpu.io.journal import FileBroker
    from streambench_tpu.io.redis_schema import (
        as_redis,
        read_seen_counts,
        seed_campaigns,
    )
    from streambench_tpu.ops import windowcount as wc
    from streambench_tpu.parallel import (
        ShardedHLLEngine,
        ShardedWindowEngine,
        build_mesh,
        collectives,
    )
    from streambench_tpu.parallel.sharded import (
        _build_scan,
        _build_scan_packed,
        data_axis_pad,
        sharded_init_state,
    )

    n = args.n_devices
    if len(jax.devices()) < n:
        print(json.dumps({"n": n, "error": "virtual device count not "
                          f"applied: {len(jax.devices())} < {n}"}),
              flush=True)
        return 1
    deadline = _T0 + args.budget_s
    data, campaign = _mesh_shape(n)
    mesh = build_mesh(data=data, campaign=campaign,
                      devices=jax.devices()[:n])
    out: dict = {"n": n, "mesh": [data, campaign]}

    import tempfile

    workdir = tempfile.mkdtemp(prefix=f"multichip{n}_")
    cfg = default_config(jax_batch_size=args.batch, jax_window_slots=16)

    # -- exact-count engine, oracle-verified ---------------------------
    r = as_redis(FakeRedisStore())
    broker = FileBroker(os.path.join(workdir, "broker"))
    gen.do_setup(r, cfg, broker=broker, events_num=args.events,
                 rng=random.Random(11), workdir=workdir)
    mapping = gen.load_ad_mapping_file(
        os.path.join(workdir, gen.AD_TO_CAMPAIGN_FILE))
    eng = ShardedWindowEngine(cfg, mapping, mesh, redis=r)
    eng.warmup()
    t0 = time.perf_counter()
    stats = StreamRunner(eng, broker.reader(cfg.kafka_topic)).run_catchup()
    eng.close()
    wall = time.perf_counter() - t0
    correct, differ, missing = gen.check_correct(r, workdir,
                                                 log=lambda s: None)
    out["exact_ev_s"] = round(stats.events / max(wall, 1e-9))
    out["exact_oracle"] = ("exact" if differ == 0 and missing == 0
                           and correct > 0 else
                           f"DIFFER={differ},MISSING={missing}")

    # -- HLL engine, verified against the single-device engine ---------
    if time.monotonic() < deadline - 30:
        r1 = as_redis(FakeRedisStore())
        broker2 = FileBroker(os.path.join(workdir, "broker_hll"))
        gen.do_setup(r1, cfg, broker=broker2, events_num=args.hll_events,
                     rng=random.Random(12), workdir=workdir)
        mapping2 = gen.load_ad_mapping_file(
            os.path.join(workdir, gen.AD_TO_CAMPAIGN_FILE))
        heng = ShardedHLLEngine(cfg, mapping2, mesh, redis=r1)
        heng.warmup()
        t0 = time.perf_counter()
        hstats = StreamRunner(
            heng, broker2.reader(cfg.kafka_topic)).run_catchup()
        heng.close()
        hwall = time.perf_counter() - t0
        r2 = as_redis(FakeRedisStore())
        seed_campaigns(r2, gen.load_ids(workdir)[0])
        ref = HLLDistinctEngine(cfg, mapping2, redis=r2)
        StreamRunner(ref, broker2.reader(cfg.kafka_topic)).run_catchup()
        ref.close()
        out["hll"] = {
            "ev_s": round(hstats.events / max(hwall, 1e-9)),
            "match": read_seen_counts(r1) == read_seen_counts(r2),
        }
    else:
        out["hll"] = {"skipped": "budget"}

    # -- collective costs + timed dispatch for the four scan arms ------
    K = cfg.jax_scan_batches
    B = args.batch + data_axis_pad(args.batch, mesh)
    C, W = cfg.jax_num_campaigns, cfg.jax_window_slots
    rng = np.random.default_rng(0)
    jt = jnp.asarray(np.concatenate(
        [rng.integers(0, C, cfg.num_ads).astype(np.int32), [-1]]))
    ad = rng.integers(0, cfg.num_ads, (K, B)).astype(np.int32)
    et = rng.integers(0, 3, (K, B)).astype(np.int32)
    tm = np.sort(rng.integers(70_000, 130_000, (K, B))).astype(np.int32)
    va = (rng.random((K, B)) < 0.95)
    word = np.stack([wc.pack_columns(ad[k], et[k], va[k])
                     for k in range(K)])
    arms = {
        "unpacked_perbatch": (_build_scan(mesh, 10_000, 60_000, 0, False),
                              (ad, et, tm, va)),
        "unpacked_hoisted": (_build_scan(mesh, 10_000, 60_000, 0, True),
                             (ad, et, tm, va)),
        "packed_perbatch": (_build_scan_packed(mesh, 10_000, 60_000, 0,
                                               False), (word, tm)),
        "packed_hoisted": (_build_scan_packed(mesh, 10_000, 60_000, 0,
                                              True), (word, tm)),
    }
    out["scan"] = {}
    for name, (fn, cols) in arms.items():
        st = sharded_init_state(C, W, mesh)
        rep = collectives.report_for(
            fn, st.counts, st.window_ids, st.watermark, st.dropped, jt,
            *cols, scan_len=K)
        arm = {"ops": rep["per_dispatch"]["ops"],
               "bytes": rep["per_dispatch"]["bytes"],
               "column_ops": rep["per_dispatch"]["column_ops"],
               "column_bytes": rep["per_dispatch"]["column_bytes"]}
        # timed dispatches, chained through the donated counts buffer
        reps = args.reps
        state = sharded_init_state(C, W, mesh)
        carry = (state.counts, state.window_ids, state.watermark,
                 state.dropped)
        o = fn(*carry, jt, *cols)  # compile + warm
        t0 = time.perf_counter()
        done = 0
        for _ in range(reps):
            o = fn(*o, jt, *cols)
            done += 1
            if time.monotonic() > deadline - 10:
                break
        jax.block_until_ready(o)
        dt = (time.perf_counter() - t0) / max(done, 1)
        arm["ms_per_dispatch"] = round(dt * 1e3, 2)
        arm["ev_s"] = round(K * args.batch / dt)
        # Per-device dispatch-time spread (ISSUE 9 straggler column):
        # one more dispatch, then observe each counts shard's readiness
        # time in device order.  max-min is the straggler evidence a
        # real mesh needs next to the collective table; on THIS virtual
        # mesh (thread slices of one core) it mostly measures the
        # sequential emulation, which the artifact note already states.
        o = fn(*o, jt, *cols)
        t0 = time.perf_counter()
        waits = []
        for sh in o[0].addressable_shards:
            jax.block_until_ready(sh.data)
            waits.append(time.perf_counter() - t0)
        arm["device_wait_ms"] = [round(w * 1e3, 3) for w in waits]
        arm["straggler_spread_ms"] = round(
            (max(waits) - min(waits)) * 1e3, 3) if waits else None
        out["scan"][name] = arm

    # -- sliding + session scan arms (ISSUE 12): the PR 7 hoist
    # treatment extended to the remaining sketch families.  The HLO
    # collective table is the claim: hoisted sliding scans issue
    # (cols+1) collectives per dispatch vs K*(cols+1) per-batch, and
    # the hoisted session scan's body is collective-free (stacked
    # post-scan merges) vs ~K*16 inside the loop.
    if time.monotonic() < deadline - 45:
        from streambench_tpu.engine.sketches import LAT_BINS
        from streambench_tpu.parallel.sketches import (
            _build_session_scan,
            _build_sliding_scan,
        )

        Cs, Ws, Ss, TD = cfg.jax_num_campaigns, 128, 10, 16
        sl_cols = (jt, jnp.int32(0), jnp.asarray(ad), jnp.asarray(et),
                   jnp.asarray(tm), jnp.asarray(va))
        out["sliding_scan"] = {}
        for name, (hoist, sliced) in {
            "legacy_perbatch": (False, False),
            "legacy_hoisted": (True, False),
            "sliced_hoisted": (True, True),
        }.items():
            if time.monotonic() > deadline - 30:
                out["sliding_scan"][name] = {"skipped": "budget"}
                continue
            counts = (jnp.zeros((Cs, Ss, Ws), jnp.int32) if sliced
                      else jnp.zeros((Cs, Ws), jnp.int32))
            stt = (counts, jnp.full((Ws,), -1, jnp.int32),
                   jnp.int32(0), jnp.int32(0),
                   jnp.zeros((Cs, TD), jnp.float32),
                   jnp.zeros((Cs, TD), jnp.float32))
            fn = _build_sliding_scan(mesh, 10_000, 1_000, 60_000, 0,
                                     hoist, sliced)
            rep = collectives.report_for(fn, *stt, *sl_cols, scan_len=K)
            entry = {"ops": rep["per_dispatch"]["ops"],
                     "bytes": rep["per_dispatch"]["bytes"],
                     "loop_ops": rep["per_loop_iteration"]["ops"]}
            o = fn(*stt, *sl_cols)  # compile + warm
            jax.block_until_ready(o[0])
            t0 = time.perf_counter()
            o = fn(*o, *sl_cols)
            jax.block_until_ready(o[0])
            dt = time.perf_counter() - t0
            entry["ms_per_dispatch"] = round(dt * 1e3, 2)
            entry["ev_s"] = round(K * args.batch / max(dt, 1e-9))
            out["sliding_scan"][name] = entry

        U, M = 1 << 10, 128
        if time.monotonic() < deadline - 30:
            users = rng.integers(0, U, (K, B)).astype(np.int32)
            sess_cols = (jnp.int32(0), jnp.asarray(users),
                         jnp.asarray(et), jnp.asarray(tm),
                         jnp.asarray(va))
            sess_state = (
                jnp.full((U,), -1, jnp.int32), jnp.zeros((U,), jnp.int32),
                jnp.zeros((U,), jnp.int32), jnp.int32(0), jnp.int32(0),
                jnp.zeros((4, 2048), jnp.int32), jnp.int32(0),
                jnp.full((M,), -1, jnp.int32),
                jnp.full((M,), -1, jnp.int32),
                jnp.int32(0), jnp.int32(0),
                jnp.zeros((LAT_BINS,), jnp.int32))
            out["session_scan"] = {}
            for name, hoist in {"perbatch": False, "hoisted": True}.items():
                fn = _build_session_scan(mesh, 30_000, 60_000, U, hoist)
                rep = collectives.report_for(fn, *sess_state, *sess_cols,
                                             scan_len=K)
                out["session_scan"][name] = {
                    "ops": rep["per_dispatch"]["ops"],
                    "bytes": rep["per_dispatch"]["bytes"],
                    "loop_ops": rep["per_loop_iteration"]["ops"]}
        # the headline the CI smoke asserts: hoisted scans carry no
        # loop-body collectives and far fewer per dispatch
        sl = out["sliding_scan"]
        if "ops" in sl.get("legacy_hoisted", {}):
            out["sliding_hoist_ops"] = {
                "hoisted": sl["legacy_hoisted"]["ops"],
                "sliced_hoisted": sl.get("sliced_hoisted", {}).get("ops"),
                "perbatch": sl["legacy_perbatch"]["ops"],
            }
        if "ops" in (out.get("session_scan") or {}).get("hoisted", {}):
            out["session_hoist_ops"] = {
                "hoisted": out["session_scan"]["hoisted"]["ops"],
                "perbatch": out["session_scan"]["perbatch"]["ops"],
            }

    # headline ratios the artifact cites (collective structure is the
    # transferable result; guard n=1 where XLA elides the collectives)
    up = out["scan"]["unpacked_hoisted"]
    pk = out["scan"]["packed_hoisted"]
    if up["column_bytes"]:
        out["packed_col_ratio"] = round(
            pk["column_bytes"] / up["column_bytes"], 4)
    if up["column_ops"]:
        # 4 unpacked wire columns: per-column gather count per dispatch
        out["gathers_per_col"] = {
            "hoisted": up["column_ops"] / 4,
            "perbatch": out["scan"]["unpacked_perbatch"]["column_ops"] / 4,
        }
    out["wall_s"] = round(time.monotonic() - _T0, 1)
    print(json.dumps(out, separators=(",", ":")), flush=True)
    return 0


# ----------------------------------------------------------------------
# parent: one subprocess per rung, budget-guarded
# ----------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", default="1,2,8")
    ap.add_argument("--events", type=int, default=40_000)
    ap.add_argument("--hll-events", type=int, default=12_000)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=10,
                    help="timed dispatches per scan arm")
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: n in {1,2}, tiny event counts")
    ap.add_argument("--out", default="bench_multichip.json")
    ap.add_argument("--artifact", default="",
                    help="also write a MULTICHIP_r0x-schema artifact")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--n-devices", type=int, default=0)
    ap.add_argument("--budget-s", type=float, default=0.0)
    args = ap.parse_args()

    if args.worker:
        return _worker(args)

    budget_s = float(os.environ.get("STREAMBENCH_BENCH_BUDGET_S", "840"))
    deadline = _T0 + budget_s
    if args.smoke:
        args.devices = "1,2"
        args.events = 4_000
        args.hll_events = 2_000
        args.reps = 3
    devices = [int(d) for d in args.devices.split(",") if d]

    runs = []
    for n in devices:
        remaining = deadline - time.monotonic()
        if remaining < 60:
            log(f"rung n={n} skipped: {remaining:.0f}s left of the "
                f"{budget_s:.0f}s envelope")
            runs.append({"n": n, "skipped": "budget"})
            continue
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        flags = env.get("XLA_FLAGS", "")
        import re as _re

        flags = _re.sub(r"--xla_force_host_platform_device_count=\d+",
                        "", flags).strip()
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               "--n-devices", str(n), "--events", str(args.events),
               "--hll-events", str(args.hll_events),
               "--batch", str(args.batch), "--reps", str(args.reps),
               "--budget-s", str(max(remaining - 15, 45))]
        log(f"rung n={n}: {remaining:.0f}s left")
        try:
            proc = subprocess.run(
                cmd, env=env, cwd=REPO, capture_output=True, text=True,
                timeout=max(remaining - 5, 50))
        except subprocess.TimeoutExpired:
            runs.append({"n": n, "error": "rung timeout"})
            continue
        sys.stderr.write(proc.stderr[-2000:])
        line = ""
        for ln in proc.stdout.strip().splitlines():
            if ln.startswith("{"):
                line = ln
        if proc.returncode != 0 or not line:
            runs.append({"n": n, "error":
                         f"rc={proc.returncode}: {proc.stdout[-200:]}"})
            continue
        runs.append(json.loads(line))
        # progressive emission: a kill after any rung leaves a parseable
        # summary of everything completed so far
        print(compact_line(_summary(runs, budget_s)), flush=True)

    summary = _summary(runs, budget_s)
    try:
        with open(args.out + ".tmp", "w") as f:
            json.dump(summary, f, indent=1)
        os.replace(args.out + ".tmp", args.out)
    except OSError as e:
        log(f"could not write {args.out}: {e}")
    tail = compact_line(summary)
    print(tail, flush=True)
    if args.artifact:
        art = {
            "n_devices": max((r["n"] for r in runs if "error" not in r
                              and "skipped" not in r), default=0),
            "rc": 0 if summary["ok"] else 1,
            "ok": summary["ok"],
            "skipped": False,
            "tail": tail,
        }
        with open(args.artifact, "w") as f:
            json.dump(art, f, indent=2)
        log(f"artifact written: {args.artifact}")
    return 0 if summary["ok"] else 1


def _summary(runs: list, budget_s: float) -> dict:
    done = [r for r in runs if "error" not in r and "skipped" not in r]
    ok = bool(done) and all(
        r.get("exact_oracle") == "exact"
        and r.get("hll", {}).get("match", True) is True for r in done)
    return {
        "multichip": True,
        "platform": "cpu-virtual-mesh",
        "note": ("virtual host mesh: collective table (ops/bytes per "
                 "dispatch, from compiled HLO) is the transferable "
                 "result; ev/s measures 1-core emulation, not ICI"),
        "ok": ok,
        "budget_s": budget_s,
        "wall_s": round(time.monotonic() - _T0, 1),
        "runs": runs,
    }


if __name__ == "__main__":
    sys.exit(main())
